"""KubeCluster (real-K8s REST backend) against an in-proc fake API server:
verbs round-trip, and the OperationReconciler drives a run to completion
through it — proving the Cluster ABC seam holds for a real cluster
(SURVEY.md §2 Operator; §4 "no real cluster in CI")."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from polyaxon_tpu.operator import KubeApiError, KubeCluster, OperationCR, OperationReconciler
from polyaxon_tpu.operator.cluster import PodPhase


class _FakeK8sApi:
    """Tiny subset of the K8s REST API: pods/services CRUD + logs, with a
    resourceVersion journal so watches resume (and can be made to drop
    mid-burst / return 410 Gone, for the churn tests)."""

    def __init__(self):
        self.objects = {"pods": {}, "services": {}}
        self.logs = {}
        self.requests = []
        self.rv = 0
        self.journal = []  # (rv, type, deep-copied pod snapshot)
        self.drop_stream_after = None  # close watch stream after N events
        self.compacted_below = 0       # watches older than this get 410
        handler_self = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, payload, raw=False):
                body = payload.encode() if raw else json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type",
                                 "text/plain" if raw else "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _parts(self):
                u = urlparse(self.path)
                return u.path.strip("/").split("/"), parse_qs(u.query)

            def do_POST(self):
                parts, _ = self._parts()
                handler_self.requests.append(("POST", self.path))
                plural = parts[4]
                body = json.loads(self.rfile.read(
                    int(self.headers["Content-Length"])))
                name = body["metadata"]["name"]
                if name in handler_self.objects[plural]:
                    self._send(409, {"reason": "AlreadyExists"})
                    return
                body.setdefault("status", {"phase": "Pending"})
                handler_self.objects[plural][name] = body
                handler_self._journal("ADDED", plural, body)
                self._send(201, body)

            def do_GET(self):
                parts, query = self._parts()
                handler_self.requests.append(("GET", self.path))
                plural = parts[4]
                if len(parts) == 5:  # list or watch
                    sel = query.get("labelSelector", [""])[0]
                    wanted = dict(kv.split("=") for kv in sel.split(",") if kv)
                    items = [
                        o for o in handler_self.objects[plural].values()
                        if all((o["metadata"].get("labels") or {}).get(k) == v
                               for k, v in wanted.items())
                    ]
                    if query.get("watch", ["false"])[0] == "true":
                        rv_from = int(query.get("resourceVersion", ["0"])[0] or 0)
                        events = []
                        if rv_from and rv_from < handler_self.compacted_below:
                            # history compacted: the K8s contract is an
                            # ERROR event carrying a 410 Status
                            events.append({"type": "ERROR", "object": {
                                "kind": "Status", "code": 410,
                                "reason": "Expired"}})
                        else:
                            for erv, etype, snap in handler_self.journal:
                                if erv <= rv_from:
                                    continue
                                labels = (snap["metadata"].get("labels") or {})
                                if all(labels.get(k) == v
                                       for k, v in wanted.items()):
                                    events.append({"type": etype, "object": snap})
                            cut = handler_self.drop_stream_after
                            if cut is not None:
                                events = events[:cut]
                        body = b"".join(
                            json.dumps(e).encode() + b"\n" for e in events)
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    self._send(200, {
                        "items": items,
                        "metadata": {"resourceVersion": str(handler_self.rv)},
                    })
                elif parts[-1] == "log":
                    name = parts[5]
                    if name not in handler_self.objects[plural]:
                        self._send(404, {"reason": "NotFound"})
                    else:
                        self._send(200, handler_self.logs.get(name, ""), raw=True)
                else:
                    name = parts[5]
                    o = handler_self.objects[plural].get(name)
                    self._send(200, o) if o else self._send(404, {})

            def do_DELETE(self):
                parts, query = self._parts()
                handler_self.requests.append(("DELETE", self.path))
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)
                plural = parts[4]
                if len(parts) == 5:  # collection delete with labelSelector
                    sel = query.get("labelSelector", [""])[0]
                    wanted = dict(kv.split("=") for kv in sel.split(",") if kv)
                    doomed = [
                        n for n, o in handler_self.objects[plural].items()
                        if all((o["metadata"].get("labels") or {}).get(k) == v
                               for k, v in wanted.items())
                    ]
                    for n in doomed:
                        handler_self._journal(
                            "DELETED", plural, handler_self.objects[plural].pop(n))
                    self._send(200, {"items": doomed})
                    return
                name = parts[5]
                gone = handler_self.objects[plural].pop(name, None)
                if gone is None:
                    self._send(404, {})
                else:
                    handler_self._journal("DELETED", plural, gone)
                    self._send(200, {})

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.server_port}"

    def _journal(self, etype, plural, obj):
        """Stamp a new resourceVersion and append a snapshot event."""
        import copy

        if plural != "pods":
            return
        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        snap = copy.deepcopy(obj)
        snap.setdefault("kind", "Pod")
        self.journal.append((self.rv, etype, snap))

    def set_phase(self, name, phase, exit_code=None):
        pod = self.objects["pods"][name]
        pod["status"] = {"phase": phase}
        if exit_code is not None:
            pod["status"]["containerStatuses"] = [
                {"state": {"terminated": {"exitCode": exit_code}}}]
        self._journal("MODIFIED", "pods", pod)

    def stop(self):
        self.server.shutdown()


@pytest.fixture()
def api():
    srv = _FakeK8sApi()
    yield srv
    srv.stop()


def _pod(name, labels=None):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "labels": labels or {}},
            "spec": {"containers": [{"name": "main", "image": "x"}]}}


class TestKubeClusterVerbs:
    def test_apply_list_logs_delete(self, api):
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        kc.apply(_pod("p1", {"app": "a"}))
        kc.apply(_pod("p1", {"app": "a"}))  # 409 swallowed (re-apply)
        kc.apply(_pod("p2", {"app": "b"}))
        api.logs["p1"] = "hello from pod"
        sts = kc.pod_statuses({"app": "a"})
        assert [s.name for s in sts] == ["p1"]
        assert sts[0].phase == PodPhase.PENDING
        api.set_phase("p1", "Succeeded", exit_code=0)
        sts = kc.pod_statuses({"app": "a"})
        assert sts[0].phase == PodPhase.SUCCEEDED and sts[0].exit_code == 0
        assert kc.pod_logs("p1") == "hello from pod"
        assert kc.pod_logs("ghost") == ""
        kc.delete("Pod", "p1")
        kc.delete("Pod", "p1")  # 404 swallowed
        assert kc.pod_statuses({"app": "a"}) == []

    def test_unknown_kind_rejected(self, api):
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        with pytest.raises(ValueError, match="kind"):
            kc.apply({"kind": "Deployment", "metadata": {"name": "d"}})

    def test_http_error_surfaces(self, api):
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        with pytest.raises(KubeApiError):
            kc._request("GET", "/api/v1/namespaces/plx/pods/zzz")


class TestReconcilerOverKube:
    def test_run_to_succeeded(self, api):
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        statuses = []
        rec = OperationReconciler(
            kc, on_status=lambda u, s, m: statuses.append(s))
        labels = {"app.polyaxon.com/run": "u1"}
        rec.apply(OperationCR(run_uuid="u1", resources=[
            _pod("plx-u1-0", labels), _pod("plx-u1-1", labels),
        ]))
        rec.reconcile_once()
        assert "running" not in statuses  # pods still Pending
        api.set_phase("plx-u1-0", "Running")
        api.set_phase("plx-u1-1", "Running")
        rec.reconcile_once()
        assert statuses[-1] == "running"
        api.set_phase("plx-u1-0", "Succeeded", exit_code=0)
        api.set_phase("plx-u1-1", "Succeeded", exit_code=0)
        rec.reconcile_once()
        assert statuses[-1] == "succeeded"


def _svc(name, labels=None):
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name, "labels": labels or {}},
            "spec": {"clusterIP": "None"}}


class TestKubeTeardownPaths:
    def test_delete_selected_removes_pods_and_services(self, api):
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        kc.apply(_pod("p1", {"run": "r1"}))
        kc.apply(_pod("p2", {"run": "r1"}))
        kc.apply(_pod("other", {"run": "r2"}))
        kc.apply(_svc("s1", {"run": "r1"}))
        kc.delete_selected({"run": "r1"})
        assert kc.pod_statuses({"run": "r1"}) == []
        assert [s.name for s in kc.pod_statuses({"run": "r2"})] == ["other"]
        assert "s1" not in api.objects["services"]

    def test_apply_replaces_conflicting_pod(self, api):
        """A 409 on a Pod must REPLACE the old object (a restart's new
        attempt), not silently adopt it."""
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        kc.apply(_pod("p1", {"gen": "old"}))
        api.set_phase("p1", "Failed", exit_code=1)
        kc.apply(_pod("p1", {"gen": "new"}))
        assert api.objects["pods"]["p1"]["metadata"]["labels"]["gen"] == "new"
        # replaced pod starts Pending again, not Failed
        assert kc.pod_statuses({"gen": "new"})[0].phase == PodPhase.PENDING

    def test_reconciler_restart_recreates_pods(self, api):
        """Full RESTART path over the real-K8s verbs: failed pod with
        backoff budget -> pods torn down and re-applied fresh."""
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        statuses = []
        rec = OperationReconciler(kc, on_status=lambda u, s, m: statuses.append(s))
        labels = {"app.polyaxon.com/run": "u2"}
        rec.apply(OperationCR(run_uuid="u2", backoff_limit=1,
                              resources=[_pod("plx-u2-0", labels)]))
        api.set_phase("plx-u2-0", "Failed", exit_code=1)
        rec.reconcile_once()   # observes failure -> RESTART (budget 1)
        # the pod exists again and is Pending (fresh), not the old Failed one
        sts = kc.pod_statuses(labels)
        assert len(sts) == 1 and sts[0].phase == PodPhase.PENDING
        api.set_phase("plx-u2-0", "Succeeded", exit_code=0)
        rec.reconcile_once()
        assert statuses[-1] == "succeeded"


class TestWatchResume:
    """resourceVersion resume (VERDICT r3 missing #4): a watch that keeps
    dying mid-burst must deliver every transition exactly once, and 410
    Gone must trigger a re-list + SYNC instead of a blind retry."""

    def _start(self, kc, events):
        import threading

        stop = threading.Event()
        t = threading.Thread(
            target=kc.watch_pods,
            args=({"run": "c"},
                  lambda ty, st: events.append((ty, st.name, st.phase)), stop),
            daemon=True,
        )
        t.start()
        return stop, t

    def _wait_for(self, events, n, timeout=15):
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and len(events) < n:
            time.sleep(0.05)
        return len(events) >= n

    def test_drop_mid_burst_no_loss_no_dup(self, api):
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        kc.apply(_pod("c1", {"run": "c"}))
        events = []
        stop, t = self._start(kc, events)
        try:
            assert self._wait_for(events, 1)
            assert events[0] == ("SYNC", "c1", PodPhase.PENDING)
            # every stream now dies after delivering ONE event — a burst of
            # four transitions takes four resumed streams to drain
            api.drop_stream_after = 1
            api.set_phase("c1", "Running")
            api.set_phase("c1", "Succeeded", exit_code=0)
            kc.apply(_pod("c2", {"run": "c"}))
            api.set_phase("c2", "Running")
            assert self._wait_for(events, 5), events
            assert events[1:5] == [
                ("MODIFIED", "c1", PodPhase.RUNNING),
                ("MODIFIED", "c1", PodPhase.SUCCEEDED),
                ("ADDED", "c2", PodPhase.PENDING),
                ("MODIFIED", "c2", PodPhase.RUNNING),
            ], events
            # no duplicates trailing in
            import time

            time.sleep(0.6)
            assert len(events) == 5, events
        finally:
            stop.set()
            t.join(timeout=5)

    def test_410_gone_relists_and_resumes(self, api):
        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        kc.apply(_pod("c1", {"run": "c"}))
        events = []
        stop, t = self._start(kc, events)
        try:
            assert self._wait_for(events, 1)
            # compact away all history the client has seen; next transition
            # only reachable through a fresh list
            api.set_phase("c1", "Running")
            api.compacted_below = api.rv + 1
            assert self._wait_for(events, 2), events
            assert ("SYNC", "c1", PodPhase.RUNNING) in events[1:], events
        finally:
            stop.set()
            t.join(timeout=5)


class TestWatch:
    def test_watch_streams_pod_events(self, api):
        """watch_pods delivers events from the streaming endpoint and
        reconnects until stopped."""
        import threading
        import time

        kc = KubeCluster(host=api.url, token="t", namespace="plx")
        kc.apply(_pod("w1", {"run": "w"}))
        events = []
        stop = threading.Event()
        t = threading.Thread(
            target=kc.watch_pods,
            args=({"run": "w"}, lambda ty, st: events.append((ty, st.name)), stop),
            daemon=True,
        )
        t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not events:
            time.sleep(0.05)
        stop.set()
        t.join(timeout=5)
        # the initial list surfaces existing pods as SYNC events
        assert ("SYNC", "w1") in events, events
