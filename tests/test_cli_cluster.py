"""CLI-level e2e for the product cluster path (VERDICT r2 #2): `polyaxon run
-f examples/resnet50_ddp.yaml` must flow through manifests + reconciler +
pods — no test internals — because the default backend is ``auto`` and
pytorchjob is a distributed kind."""

import json
import os

from click.testing import CliRunner

from polyaxon_tpu.cli.main import cli

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "examples")


class TestCliAutoBackend:
    def test_run_ddp_example_routes_through_operator(self, tmp_path):
        data_dir = str(tmp_path / "plx")
        runner = CliRunner()
        result = runner.invoke(
            cli,
            [
                "run", "-f", os.path.join(EXAMPLES, "resnet50_ddp.yaml"),
                "--data-dir", data_dir,
                "--set", "component.run.worker.replicas=1",
                "--set", "component.run.runtime.model=resnet18-cifar",
                "--set", "component.run.runtime.steps=2",
                "--set", "component.run.runtime.batch_size=4",
                "--set", "component.run.runtime.checkpoint=false",
                "--set", "component.run.runtime.platform=cpu",
            ],
            catch_exceptions=False,
        )
        assert result.exit_code == 0, result.output
        assert "succeeded" in result.output
        # the operator path ran: the FakeCluster workdir holds the pods'
        # stdout logs (one per replica), written by the reconciler backend
        cluster_dir = os.path.join(data_dir, "artifacts", ".cluster")
        assert os.path.isdir(cluster_dir), result.output
        logs = [f for f in os.listdir(cluster_dir) if f.endswith(".log")]
        assert len(logs) >= 2, sorted(os.listdir(cluster_dir))

    def test_plain_job_stays_local(self, tmp_path):
        data_dir = str(tmp_path / "plx")
        spec = tmp_path / "job.yaml"
        spec.write_text(
            "version: 1.1\n"
            "kind: component\n"
            "name: hello\n"
            "run:\n"
            "  kind: job\n"
            "  container:\n"
            "    command: [python, -c, \"print('hi')\"]\n"
        )
        runner = CliRunner()
        result = runner.invoke(
            cli, ["run", "-f", str(spec), "--data-dir", data_dir],
            catch_exceptions=False,
        )
        assert result.exit_code == 0, result.output
        assert "succeeded" in result.output
        cluster_dir = os.path.join(data_dir, "artifacts", ".cluster")
        # auto backend builds the FakeCluster dir but plain jobs never
        # create pods in it
        pods = [f for f in os.listdir(cluster_dir)] if os.path.isdir(cluster_dir) else []
        assert not [f for f in pods if f.endswith(".log")], pods


class TestOpsArtifacts:
    def test_browse_and_download_local(self, tmp_path, monkeypatch):
        data_dir = str(tmp_path / "plx")
        spec = tmp_path / "job.yaml"
        spec.write_text(
            "version: 1.1\n"
            "kind: component\n"
            "name: arts\n"
            "run:\n"
            "  kind: job\n"
            "  container:\n"
            "    command: [python, -c, \"import os; open(os.path.join("
            "os.environ['PLX_ARTIFACTS_PATH'], 'model.bin'), 'w').write('W')\"]\n"
        )
        runner = CliRunner()
        result = runner.invoke(
            cli, ["run", "-f", str(spec), "--data-dir", data_dir],
            catch_exceptions=False,
        )
        assert result.exit_code == 0, result.output
        # local-mode ops commands read ./.plx relative to the cwd
        monkeypatch.chdir(tmp_path)
        os.rename(data_dir, str(tmp_path / ".plx"))
        ls = runner.invoke(cli, ["ops", "ls"], catch_exceptions=False)
        uuid = ls.output.split()[0]
        tree = runner.invoke(cli, ["ops", "artifacts", uuid],
                             catch_exceptions=False)
        assert "model.bin" in tree.output, tree.output
        dest = str(tmp_path / "out.bin")
        dl = runner.invoke(cli, ["ops", "artifacts", uuid, "--path",
                                 "model.bin", "--dest", dest],
                           catch_exceptions=False)
        assert dl.exit_code == 0, dl.output
        assert open(dest).read() == "W"
        # escape attempt is rejected
        esc = runner.invoke(cli, ["ops", "artifacts", uuid, "--path", "../.."])
        assert esc.exit_code != 0


class TestOpsCompare:
    def test_compare_two_runs(self, tmp_path, monkeypatch):
        """`ops compare` prints params/outputs side by side (the CLI face
        of the dashboard compare view)."""
        data_dir = str(tmp_path / "state")
        spec = tmp_path / "job.yaml"
        spec.write_text(
            "version: 1.1\n"
            "kind: component\n"
            "name: cmp\n"
            "inputs:\n"
            "  - {name: lr, type: float}\n"
            "run:\n"
            "  kind: job\n"
            "  container:\n"
            "    command: [python, -c, \"import os, json; "
            "open(os.path.join(os.environ['PLX_ARTIFACTS_PATH'], "
            "'outputs.json'), 'w').write(json.dumps({'loss': "
            "float(json.loads(os.environ['PLX_PARAMS'])['lr']) * 2}))\"]\n"
        )
        runner = CliRunner()
        for lr in ("0.1", "0.2"):
            result = runner.invoke(
                cli, ["run", "-f", str(spec), "-P", f"lr={lr}",
                      "--data-dir", data_dir],
                catch_exceptions=False,
            )
            assert result.exit_code == 0, result.output
        monkeypatch.chdir(tmp_path)
        os.rename(data_dir, str(tmp_path / ".plx"))
        ls = runner.invoke(cli, ["ops", "ls"], catch_exceptions=False)
        uuids = [line.split()[0] for line in ls.output.strip().splitlines()]
        assert len(uuids) == 2
        out = runner.invoke(cli, ["ops", "compare", *uuids],
                            catch_exceptions=False)
        assert out.exit_code == 0, out.output
        lines = out.output.splitlines()
        assert lines[1].startswith("status")
        assert any(line.startswith("lr") and "0.1" in line and "0.2" in line
                   for line in lines), out.output
        assert any(line.startswith("loss") and "0.2" in line and "0.4" in line
                   for line in lines), out.output
        # one uuid is an error, not a degenerate table
        single = runner.invoke(cli, ["ops", "compare", uuids[0]])
        assert single.exit_code != 0
