"""Multislice (num_slices > 1) rendering + e2e (SURVEY.md §5 "distributed
communication backend", VERDICT r2 #7): pods span slices, jax.distributed
env is global, and the megascale/DCN transport env + per-slice node pools
are injected."""

import sys
import time

from polyaxon_tpu.api.store import Store
from polyaxon_tpu.compiler.resolver import resolve
from polyaxon_tpu.polyaxonfile import check_polyaxonfile
from polyaxon_tpu.scheduler.agent import LocalAgent


def _tpujob_spec(num_slices=2, command=None):
    return check_polyaxonfile({
        "kind": "operation",
        "name": "ms",
        "component": {
            "kind": "component",
            "run": {
                "kind": "tpujob",
                "accelerator": "v5e",
                "topology": "2x2",       # 1 host per slice
                "numSlices": num_slices,
                "container": {
                    "command": command or [sys.executable, "-c", "print('hi')"],
                },
            },
        },
    }).to_dict()


class TestMultisliceRendering:
    def test_megascale_env_and_slice_pools(self):
        spec = _tpujob_spec(num_slices=2)
        resolved = resolve(spec, run_uuid="u" * 32, project="p",
                           artifacts_path="/tmp/x")
        resources = resolved.k8s_resources()
        pods = [r for r in resources if r["kind"] == "Pod"]
        assert len(pods) == 2  # 2 slices x 1 host each
        for i, pod in enumerate(pods):
            env = {e["name"]: e["value"]
                   for e in pod["spec"]["containers"][0]["env"]}
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(i)
            assert env["PLX_SLICE_ID"] == str(i)
            assert ":8080" in env["MEGASCALE_COORDINATOR_ADDRESS"]
            # one jax.distributed job across all slices
            assert env["PLX_NUM_PROCESSES"] == "2"
            assert env["PLX_PROCESS_ID"] == str(i)
            assert pod["spec"]["nodeSelector"]["app.polyaxon.com/slice-id"] == str(i)

    def test_single_slice_has_no_megascale(self):
        spec = _tpujob_spec(num_slices=1)
        resolved = resolve(spec, run_uuid="u" * 32, project="p",
                           artifacts_path="/tmp/x")
        pods = [r for r in resolved.k8s_resources() if r["kind"] == "Pod"]
        env = {e["name"]: e["value"]
               for e in pods[0]["spec"]["containers"][0]["env"]}
        assert "MEGASCALE_NUM_SLICES" not in env
        assert "app.polyaxon.com/slice-id" not in pods[0]["spec"].get("nodeSelector", {})


class TestMultisliceE2E:
    def test_two_slice_pods_run_with_env(self, tmp_path):
        """FakeCluster e2e: a 2-slice tpujob's pods each see their slice's
        megascale env and the run succeeds."""
        check_cmd = [
            sys.executable, "-c",
            "import os; assert os.environ['MEGASCALE_NUM_SLICES'] == '2'; "
            "assert os.environ['MEGASCALE_SLICE_ID'] == os.environ['PLX_SLICE_ID']; "
            "print('slice', os.environ['PLX_SLICE_ID'], 'ok')",
        ]
        store = Store(":memory:")
        agent = LocalAgent(store, artifacts_root=str(tmp_path), backend="cluster",
                           poll_interval=0.05)
        uuid = store.create_run("p", spec=_tpujob_spec(2, check_cmd), name="ms")["uuid"]
        deadline = time.monotonic() + 120
        status = None
        try:
            while time.monotonic() < deadline:
                agent.tick()
                status = store.get_run(uuid)["status"]
                if status in ("succeeded", "failed", "stopped"):
                    break
                time.sleep(0.05)
            assert status == "succeeded", store.get_statuses(uuid)
            envs = agent.cluster.launched_env
            slice_ids = sorted(e["MEGASCALE_SLICE_ID"] for e in envs.values())
            assert slice_ids == ["0", "1"]
        finally:
            agent.stop()
