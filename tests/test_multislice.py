"""Multislice (num_slices > 1) rendering + e2e (SURVEY.md §5 "distributed
communication backend", VERDICT r2 #7): pods span slices, jax.distributed
env is global, and the megascale/DCN transport env + per-slice node pools
are injected. Since ISSUE 13 the suite is also NUMERIC: build_mesh honors
num_slices (slice-major device order, data/fsdp over DCN) and a
2-virtual-slice training run reaches loss parity with the flat mesh."""

import sys
import time

import pytest

from polyaxon_tpu.api.store import Store
from polyaxon_tpu.compiler.resolver import resolve
from polyaxon_tpu.polyaxonfile import check_polyaxonfile
from polyaxon_tpu.scheduler.agent import LocalAgent


def _tpujob_spec(num_slices=2, command=None):
    return check_polyaxonfile({
        "kind": "operation",
        "name": "ms",
        "component": {
            "kind": "component",
            "run": {
                "kind": "tpujob",
                "accelerator": "v5e",
                "topology": "2x2",       # 1 host per slice
                "numSlices": num_slices,
                "container": {
                    "command": command or [sys.executable, "-c", "print('hi')"],
                },
            },
        },
    }).to_dict()


class TestMultisliceRendering:
    def test_megascale_env_and_slice_pools(self):
        spec = _tpujob_spec(num_slices=2)
        resolved = resolve(spec, run_uuid="u" * 32, project="p",
                           artifacts_path="/tmp/x")
        resources = resolved.k8s_resources()
        pods = [r for r in resources if r["kind"] == "Pod"]
        assert len(pods) == 2  # 2 slices x 1 host each
        for i, pod in enumerate(pods):
            env = {e["name"]: e["value"]
                   for e in pod["spec"]["containers"][0]["env"]}
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(i)
            assert env["PLX_SLICE_ID"] == str(i)
            assert ":8080" in env["MEGASCALE_COORDINATOR_ADDRESS"]
            # one jax.distributed job across all slices
            assert env["PLX_NUM_PROCESSES"] == "2"
            assert env["PLX_PROCESS_ID"] == str(i)
            assert pod["spec"]["nodeSelector"]["app.polyaxon.com/slice-id"] == str(i)

    def test_single_slice_has_no_megascale(self):
        spec = _tpujob_spec(num_slices=1)
        resolved = resolve(spec, run_uuid="u" * 32, project="p",
                           artifacts_path="/tmp/x")
        pods = [r for r in resolved.k8s_resources() if r["kind"] == "Pod"]
        env = {e["name"]: e["value"]
               for e in pods[0]["spec"]["containers"][0]["env"]}
        assert "MEGASCALE_NUM_SLICES" not in env
        assert "app.polyaxon.com/slice-id" not in pods[0]["spec"].get("nodeSelector", {})


class TestMultisliceMesh:
    """build_mesh honors num_slices (ROADMAP item 3: previously ignored)."""

    def _slice_of(self, num_slices=2):
        import jax

        from polyaxon_tpu.parallel import device_slice_ids

        devs = jax.devices()
        return {d: s for d, s in zip(devs,
                                     device_slice_ids(devs, num_slices))}

    def test_slice_major_order_inner_axes_intra_slice(self):
        """data spans both (virtual) slices — DCN traffic — while every
        model-axis neighbor pair sits inside ONE slice (ICI)."""
        from polyaxon_tpu.parallel import build_mesh

        mesh = build_mesh({"data": 2, "fsdp": 2, "model": 2}, num_slices=2)
        by = self._slice_of(2)
        arr = mesh.devices
        for di in range(2):
            for fi in range(2):
                col = arr[di, fi, 0, 0, 0, :]
                assert len({by[d] for d in col}) == 1, (
                    "model axis crossed a slice boundary")
        assert {by[d] for d in arr[:, 0, 0, 0, 0, 0]} == {0, 1}

    def test_fsdp_carries_the_slice_dim_when_data_is_1(self):
        from polyaxon_tpu.parallel import build_mesh

        mesh = build_mesh({"fsdp": 4, "model": 2}, num_slices=2)
        by = self._slice_of(2)
        arr = mesh.devices
        assert {by[d] for d in arr[0, :, 0, 0, 0, 0]} == {0, 1}
        for fi in range(4):
            assert len({by[d] for d in arr[0, fi, 0, 0, 0, :]}) == 1

    def test_intra_slice_axes_cannot_span_dcn(self):
        from polyaxon_tpu.parallel import build_mesh

        with pytest.raises(ValueError, match="data.?fsdp|data\\*fsdp"):
            build_mesh({"model": 8}, num_slices=2)

    def test_indivisible_virtual_slices_rejected(self):
        import jax

        from polyaxon_tpu.parallel import build_mesh

        with pytest.raises(ValueError):
            build_mesh({"data": 3}, devices=jax.devices()[:3], num_slices=2)


class TestMultisliceNumeric:
    def test_two_virtual_slice_loss_parity_vs_flat_mesh(self):
        """The ISSUE 13 acceptance numeric: the SAME training config on a
        2-virtual-slice mesh and on the flat mesh reaches loss parity —
        slice-major placement changes physical neighbors, never the
        logical SPMD program."""
        import numpy as np

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.train import (
            DataConfig, OptimizerConfig, Trainer, TrainerConfig, make_batches,
        )

        cfg = llama.LLAMA_TINY

        def run(num_slices):
            tcfg = TrainerConfig(
                model=cfg,
                optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=1,
                                          total_steps=3),
                batch_size=8, seq_len=16,
                parallelism={"data": 2, "fsdp": 2, "model": 2},
                num_slices=num_slices)
            tr = Trainer(tcfg)
            data = make_batches(DataConfig(
                kind="synthetic-lm", batch_size=8, seq_len=16,
                vocab_size=cfg.vocab_size), tr.mesh)
            _, metrics = tr.fit(data, num_steps=3)
            return metrics["loss"]

        multi = run(num_slices=2)
        flat = run(num_slices=1)
        assert np.isfinite(multi)
        assert abs(multi - flat) < 1e-5, (multi, flat)


class TestMultisliceE2E:
    def test_two_slice_pods_run_with_env(self, tmp_path):
        """FakeCluster e2e: a 2-slice tpujob's pods each see their slice's
        megascale env and the run succeeds."""
        check_cmd = [
            sys.executable, "-c",
            "import os; assert os.environ['MEGASCALE_NUM_SLICES'] == '2'; "
            "assert os.environ['MEGASCALE_SLICE_ID'] == os.environ['PLX_SLICE_ID']; "
            "print('slice', os.environ['PLX_SLICE_ID'], 'ok')",
        ]
        store = Store(":memory:")
        agent = LocalAgent(store, artifacts_root=str(tmp_path), backend="cluster",
                           poll_interval=0.05)
        uuid = store.create_run("p", spec=_tpujob_spec(2, check_cmd), name="ms")["uuid"]
        deadline = time.monotonic() + 120
        status = None
        try:
            while time.monotonic() < deadline:
                agent.tick()
                status = store.get_run(uuid)["status"]
                if status in ("succeeded", "failed", "stopped"):
                    break
                time.sleep(0.05)
            assert status == "succeeded", store.get_statuses(uuid)
            envs = agent.cluster.launched_env
            slice_ids = sorted(e["MEGASCALE_SLICE_ID"] for e in envs.values())
            assert slice_ids == ["0", "1"]
        finally:
            agent.stop()
