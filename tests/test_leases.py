"""ISSUE 4 tier-1 units: agent leases + fencing tokens, write-ahead launch
intents, orphan adoption, cold-start resync, graceful drain, atomic
checkpoint manifests — and a fast (<30s) agent-kill smoke so the slow
kill-the-agent soak (tests/test_chaos_soak.py) is not the only guard."""

import os
import sys
import time

import pytest

from polyaxon_tpu.api.store import FencedStore, StaleLeaseError, Store
from polyaxon_tpu.operator import FakeCluster
from polyaxon_tpu.polyaxonfile import check_polyaxonfile
from polyaxon_tpu.resilience import FaultyStore
from polyaxon_tpu.scheduler.agent import LocalAgent


def _job_spec(name, sleep=0.0, max_retries=None):
    cmd = (f"import time; time.sleep({sleep}); print('done')"
           if sleep else "print('done')")
    spec = {"kind": "operation", "name": name,
            "component": {"kind": "component", "run": {
                "kind": "job",
                "container": {"command": [sys.executable, "-c", cmd]}}}}
    if max_retries is not None:
        spec["termination"] = {"maxRetries": max_retries}
    return check_polyaxonfile(spec).to_dict()


def _wait(pred, timeout=30.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# lease verbs + fencing in the store
# ---------------------------------------------------------------------------


class TestAgentLeases:
    def test_acquire_renew_release_roundtrip(self):
        store = Store(":memory:")
        lease = store.acquire_lease("scheduler", "a1", ttl=30)
        assert lease["holder"] == "a1" and lease["token"] == 1
        # held: a second holder is refused
        assert store.acquire_lease("scheduler", "a2", ttl=30) is None
        assert store.renew_lease("scheduler", "a1", lease["token"])
        # explicit release -> instant successor acquisition, newer token
        assert store.release_lease("scheduler", "a1", lease["token"])
        lease2 = store.acquire_lease("scheduler", "a2", ttl=30)
        assert lease2["holder"] == "a2"
        assert lease2["token"] > lease["token"]

    def test_ttl_expiry_allows_takeover_and_bumps_token(self):
        store = Store(":memory:")
        lease = store.acquire_lease("scheduler", "a1", ttl=0.05)
        time.sleep(0.1)
        assert store.get_lease("scheduler")["expired"]
        lease2 = store.acquire_lease("scheduler", "a2", ttl=30)
        assert lease2 is not None and lease2["token"] > lease["token"]
        # the loser's renewal is rejected — it must demote, not limp on
        assert not store.renew_lease("scheduler", "a1", lease["token"])

    def test_token_monotonic_across_release(self):
        """A release deletes the row but NOT the counter: a token can
        never be reissued, so 'row missing' can't launder an old token."""
        store = Store(":memory:")
        tokens = []
        for holder in ("a", "b", "c"):
            lease = store.acquire_lease("scheduler", holder, ttl=30)
            tokens.append(lease["token"])
            store.release_lease("scheduler", holder, lease["token"])
        assert tokens == sorted(set(tokens))

    def test_self_reacquire_bumps_token(self):
        store = Store(":memory:")
        l1 = store.acquire_lease("scheduler", "a1", ttl=30)
        l2 = store.acquire_lease("scheduler", "a1", ttl=30)
        assert l2["token"] > l1["token"]
        # the pre-reacquisition token is dead
        r = store.create_run("p", spec={}, name="x")
        with pytest.raises(StaleLeaseError):
            store.transition(r["uuid"], "compiled",
                             fence=("scheduler", l1["token"]))


class TestFencing:
    def _takeover(self, store):
        l1 = store.acquire_lease("scheduler", "a1", ttl=0.01)
        time.sleep(0.05)
        l2 = store.acquire_lease("scheduler", "a2", ttl=30)
        assert l2 is not None
        return l1, l2

    def test_stale_transition_many_rejected_whole_batch(self):
        store = Store(":memory:")
        runs = [store.create_run("p", spec={}, name=f"r{i}")
                for i in range(3)]
        l1, l2 = self._takeover(store)
        events = []
        store.add_transition_listener(lambda u, s: events.append((u, s)))
        with pytest.raises(StaleLeaseError):
            store.transition_many(
                [(r["uuid"], "compiled") for r in runs],
                fence=("scheduler", l1["token"]))
        # nothing moved, no listener fired, the rejection was counted
        assert all(store.get_run(r["uuid"])["status"] == "created"
                   for r in runs)
        assert events == []
        assert store.stats["fence_rejections"] == 1
        # the live holder's batch lands
        store.transition_many([(r["uuid"], "compiled") for r in runs],
                              fence=("scheduler", l2["token"]))
        assert all(store.get_run(r["uuid"])["status"] == "compiled"
                   for r in runs)

    def test_stale_create_runs_and_update_rejected(self):
        store = Store(":memory:")
        r = store.create_run("p", spec={}, name="x")
        l1, _ = self._takeover(store)
        stale = ("scheduler", l1["token"])
        with pytest.raises(StaleLeaseError):
            store.create_runs("p", [dict(spec={}, name="child")], fence=stale)
        with pytest.raises(StaleLeaseError):
            store.update_run(r["uuid"], fence=stale, meta={"k": "v"})
        with pytest.raises(StaleLeaseError):
            store.record_launch_intent(r["uuid"], "a1", l1["token"],
                                       fence=stale)
        assert store.count_runs(project="p") == 1
        assert store.stats["fence_rejections"] == 3

    def test_fenced_store_proxy_demotes_on_rejection(self):
        store = Store(":memory:")
        r = store.create_run("p", spec={}, name="x")
        l1, _ = self._takeover(store)
        demoted = []
        proxy = FencedStore(store, lambda: ("scheduler", l1["token"]),
                            on_stale=lambda: demoted.append(True))
        with pytest.raises(StaleLeaseError):
            proxy.transition(r["uuid"], "compiled")
        assert demoted == [True]
        # reads always pass through
        assert proxy.get_run(r["uuid"])["status"] == "created"
        # no lease -> unfenced (direct-call test semantics preserved)
        free = FencedStore(store, lambda: None)
        run, changed = free.transition(r["uuid"], "compiled")
        assert changed


class TestFileDbFencing:
    def test_fence_check_atomic_across_connections(self, tmp_path):
        """Two Store instances on ONE file db (supervisor double-start):
        the fence check must be atomic with the guarded write — after B's
        acquisition commits, A's fenced write is rejected even though A
        read its token before ever touching this connection's
        transaction (bare SELECTs run in autocommit)."""
        db = str(tmp_path / "shared.sqlite")
        a, b = Store(db), Store(db)
        la = a.acquire_lease("scheduler", "a", ttl=0.01)
        time.sleep(0.05)
        lb = b.acquire_lease("scheduler", "b", ttl=30)
        assert lb["token"] > la["token"]
        r = b.create_run("p", spec={}, name="x",
                         fence=("scheduler", lb["token"]))
        with pytest.raises(StaleLeaseError):
            a.transition(r["uuid"], "compiled",
                         fence=("scheduler", la["token"]))
        # the winner's writes keep landing
        _, changed = b.transition(r["uuid"], "compiled",
                                  fence=("scheduler", lb["token"]))
        assert changed
        assert b.get_run(r["uuid"])["status"] == "compiled"


class TestFaultyStoreLeaseVerbs:
    def test_lease_verbs_gated_under_sqlite_busy(self):
        import sqlite3

        store = FaultyStore(Store(":memory:"), seed=3, fault_rate=1.0,
                            max_faults=3)
        failures = 0
        lease = None
        for _ in range(10):  # the agent's standby loop: retry next wake
            try:
                lease = store.acquire_lease("scheduler", "a1", ttl=30)
                break
            except sqlite3.OperationalError:
                failures += 1
        assert failures == 3 and lease is not None
        assert "acquire_lease" in store.injected
        # renewal rides the same gate (budget exhausted -> clean path)
        assert store.renew_lease("scheduler", "a1", lease["token"])

    def test_shard_lease_verbs_gated_too(self):
        """ISSUE 6: the batched renewal heartbeat and the fair-share
        listing behind shard acquisition/rebalance are chaos-testable —
        both gated, both surviving an injected SQLITE_BUSY burst."""
        import sqlite3

        store = FaultyStore(Store(":memory:"), seed=5, fault_rate=1.0,
                            max_faults=2)
        lease = None
        for _ in range(10):
            try:
                lease = store.acquire_lease("shard-0", "a1", ttl=30)
                break
            except sqlite3.OperationalError:
                pass
        assert lease is not None
        for verb, call in (
            ("renew_leases",
             lambda: store.renew_leases([("shard-0", lease["token"])], "a1")),
            ("list_leases", lambda: store.list_leases("shard-")),
        ):
            store._max_faults = store._faults + 1  # re-arm: one more fault
            out = None
            for _ in range(10):  # the probe's retry-next-cycle path
                try:
                    out = call()
                    break
                except sqlite3.OperationalError:
                    pass
            assert out, (verb, out)
            assert verb in store.injected

    def test_replication_verbs_gated_too(self, tmp_path):
        """ISSUE 7: the standby's tail (get_changelog/apply_changelog),
        the snapshot writer and promotion ride the same SQLITE_BUSY gate
        — a blip costs one poll, never the applied-seq watermark."""
        import sqlite3

        inner = Store(":memory:")
        run = inner.create_run("p", spec={"component": {
            "run": {"kind": "job", "container": {"command": ["true"]}}}})
        store = FaultyStore(inner, seed=9, fault_rate=1.0, max_faults=0)
        standby = Store(":memory:")
        flaky_standby = FaultyStore(standby, seed=9, fault_rate=1.0,
                                    max_faults=0)
        for gated, verb, call in (
            (store, "get_changelog", lambda: store.get_changelog(0, 100)),
            (flaky_standby, "apply_changelog",
             lambda: [flaky_standby.apply_changelog(
                 inner.get_changelog(0, 100))]),
            (store, "snapshot", lambda: store.snapshot(str(tmp_path))),
            (store, "promote", lambda: store.promote()),
            (store, "changelog_span", lambda: store.changelog_span()),
        ):
            gated._max_faults = gated._faults + 1  # re-arm: one fault
            out = None
            for _ in range(10):
                try:
                    out = call()
                    break
                except sqlite3.OperationalError:
                    pass
            assert out, (verb, out)
            assert verb in gated.injected
        # the retried replay converged despite the weather around it (the
        # applied-seq watermark absorbed the re-poll — no double apply)
        assert standby.get_run(run["uuid"]) is not None
        assert len(standby.get_statuses(run["uuid"])) == \
            len(inner.get_statuses(run["uuid"]))


# ---------------------------------------------------------------------------
# write-ahead launch intents: replay, adoption, slice loss
# ---------------------------------------------------------------------------


class TestLaunchIntents:
    def _scheduled_cluster_run(self, store, agent, name="j", sleep=0.0,
                               max_retries=None):
        """A run compiled by the real compiler, walked to 'scheduled'
        WITHOUT any cluster call — the state an agent dies in right after
        committing its launch intent."""
        run = store.create_run("p", spec=_job_spec(name, sleep=sleep,
                                                   max_retries=max_retries),
                               name=name)
        uuid = run["uuid"]
        assert agent._compile(store.get_run(uuid)) == "compiled"
        store.transition_many([(uuid, "queued"), (uuid, "scheduled")])
        return uuid

    def test_intent_replay_relaunches_without_duplicates(self, tmp_path):
        """Crash between the intent commit and the cluster accepting the
        manifests: the successor's resync must relaunch (attempt 2) —
        exactly one live pod set, run completes."""
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".c"))
        agent1 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        uuid = self._scheduled_cluster_run(store, agent1, "replay")
        # the dead agent got exactly this far: intent on disk, no pods
        store.record_launch_intent(uuid, "dead-agent", None)
        assert cluster.launch_counts.get(uuid) is None

        agent2 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        agent2.cold_start_resync()
        intent = store.get_launch_intent(uuid)
        assert intent["state"] == "launched"
        assert intent["attempt"] == 2  # replay bumped it
        assert cluster.launch_counts.get(uuid, 0) >= 1
        assert cluster.duplicate_applies == []
        try:
            _wait(lambda: (agent2.tick() or True) and
                  store.get_run(uuid)["status"] in
                  ("succeeded", "failed", "stopped"),
                  timeout=60, interval=0.05, msg="replayed run terminal")
            assert store.get_run(uuid)["status"] == "succeeded", \
                store.get_statuses(uuid)
        finally:
            agent2.stop()

    def test_adoption_reowns_without_relaunch(self, tmp_path):
        """Pods alive across the restart: the successor re-tracks and
        re-owns (meta.owner -> new lease) without ONE extra pod apply."""
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".c"))
        agent1 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        uuid = store.create_run("p", spec=_job_spec("adoptee", sleep=3.0),
                                name="adoptee")["uuid"]
        _wait(lambda: (agent1.tick() or True)
              and store.get_run(uuid)["status"] == "running",
              timeout=30, interval=0.05, msg="run running")
        applies_before = cluster.launch_counts[uuid]
        owner_before = store.get_run(uuid)["meta"]["owner"]
        assert owner_before["lease_id"] == agent1._lease_id
        assert store.get_launch_intent(uuid)["state"] == "launched"

        agent2 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        agent2.cold_start_resync()
        try:
            assert agent2.reconciler.is_tracked(uuid)
            assert cluster.launch_counts[uuid] == applies_before  # no re-apply
            assert cluster.duplicate_applies == []
            owner = store.get_run(uuid)["meta"]["owner"]
            assert owner["lease_id"] == agent2._lease_id
            assert owner["attempt"] == owner_before["attempt"]  # adoption != launch
            _wait(lambda: (agent2.tick() or True)
                  and store.get_run(uuid)["status"] == "succeeded",
                  timeout=60, interval=0.05, msg="adopted run succeeds")
        finally:
            agent2.stop()

    def test_launched_but_vanished_routes_through_retry_budget(self, tmp_path):
        """state='launched' with the pod set gone = slice loss while
        nobody watched: retrying -> queued while budget remains, and the
        rerun is a NEW launch attempt (not a duplicate)."""
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".c"))
        agent1 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        uuid = store.create_run(
            "p", spec=_job_spec("lost", sleep=5.0, max_retries=2),
            name="lost")["uuid"]
        _wait(lambda: (agent1.tick() or True)
              and store.get_run(uuid)["status"] == "running",
              timeout=30, interval=0.05, msg="run running")
        # the cluster loses the whole pod set while the agent is dead
        cluster.delete_selected({"app.polyaxon.com/run": uuid})

        agent2 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        agent2.cold_start_resync()
        try:
            types = [c["type"] for c in store.get_statuses(uuid)]
            assert "retrying" in types, types
            assert store.get_run(uuid)["status"] == "queued"
            _wait(lambda: (agent2.tick() or True)
                  and store.get_run(uuid)["status"] == "succeeded",
                  timeout=60, interval=0.05, msg="rerun succeeds")
            assert store.get_launch_intent(uuid)["attempt"] == 2
            assert cluster.duplicate_applies == []
        finally:
            agent2.stop()

    def test_scheduled_but_no_intent_requeues_without_burning_budget(
            self, tmp_path):
        """Crash in the window between the 'scheduled' transition and the
        intent commit: the write-ahead intent precedes the first cluster
        call, so nothing launched — the successor must re-queue (a normal
        launch) and NOT classify it as slice loss, which would burn retry
        budget a zero-maxRetries run doesn't have."""
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".c"))
        agent1 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        # the dead agent got exactly this far: scheduled, NO intent row
        uuid = self._scheduled_cluster_run(store, agent1, "preintent")
        assert store.get_launch_intent(uuid) is None

        agent2 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        agent2.cold_start_resync()
        try:
            assert store.get_run(uuid)["status"] == "queued"
            types = [c["type"] for c in store.get_statuses(uuid)]
            assert "retrying" not in types, types  # no budget burned
            _wait(lambda: (agent2.tick() or True)
                  and store.get_run(uuid)["status"] == "succeeded",
                  timeout=60, interval=0.05, msg="requeued run succeeds")
            assert store.get_launch_intent(uuid)["attempt"] == 1
            assert cluster.duplicate_applies == []
        finally:
            agent2.stop()

    def test_vanished_without_budget_fails_loudly(self, tmp_path):
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".c"))
        agent1 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        uuid = store.create_run("p", spec=_job_spec("doomed", sleep=5.0),
                                name="doomed")["uuid"]
        _wait(lambda: (agent1.tick() or True)
              and store.get_run(uuid)["status"] == "running",
              timeout=30, interval=0.05, msg="run running")
        cluster.delete_selected({"app.polyaxon.com/run": uuid})
        agent2 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05)
        agent2.cold_start_resync()
        row = store.get_run(uuid)
        assert row["status"] == "failed"
        assert "no retry budget" in store.get_statuses(uuid)[-1]["message"]


class TestTerminatingPodsNotAdoptable:
    def test_adopt_ignores_terminating_pods(self, tmp_path):
        """On real K8s DELETE returns before etcd removal, so a
        just-deleted pod set still lists (Terminating). Adoption must not
        re-track it — those pods die moments later and would read as a
        phantom slice failure burning a retry attempt. FakeCluster's
        synchronous delete can't show this window, so stub the listing."""
        from polyaxon_tpu.operator import (FakeCluster as FC, OperationCR,
                                           OperationReconciler, PodPhase)
        from polyaxon_tpu.operator.cluster import PodStatus

        cluster = FC(str(tmp_path / ".c"))
        dying = [PodStatus("old-0", PodPhase.RUNNING, terminating=True)]
        real_statuses = cluster.pod_statuses
        cluster.pod_statuses = (  # Terminating leftovers + whatever is real
            lambda sel: dying + real_statuses(sel))
        rec = OperationReconciler(cluster)
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "new-0",
                            "labels": {"app.polyaxon.com/run": "u9"}},
               "spec": {"containers": [{
                   "name": "c", "command": [sys.executable, "-c", "pass"]}]}}
        adopted = rec.adopt(OperationCR(run_uuid="u9", resources=[pod]))
        # nothing adoptable -> fell through to a fresh apply
        assert adopted is False
        assert rec.is_tracked("u9")
        assert any(s.name == "new-0" for s in real_statuses(
            {"app.polyaxon.com/run": "u9"}))


# ---------------------------------------------------------------------------
# cold-start resync: the wait queue comes back in pre-crash order
# ---------------------------------------------------------------------------


class TestColdStartResync:
    NOOP = {"kind": "operation",
            "component": {"kind": "component", "name": "noop",
                          "run": {"kind": "job",
                                  "container": {"command": ["true"]}}}}

    def test_wait_queue_rebuilt_in_exact_precrash_order(self, tmp_path):
        store = Store(":memory:")
        # max_parallel=0: every run parks in the wait queue
        agent1 = LocalAgent(store, str(tmp_path), max_parallel=0)
        uuids = [store.create_run("p", spec=self.NOOP, name=f"q{i}")["uuid"]
                 for i in range(15)]
        for _ in range(8):
            with agent1._dirty_lock:
                dirty, agent1._dirty = agent1._dirty, set()
            if not dirty:
                break
            agent1._tick_dirty(dirty)
        order_before = [u for u, _ in agent1._pending]
        assert order_before == uuids

        agent2 = LocalAgent(store, str(tmp_path), max_parallel=0)
        agent2.cold_start_resync()
        assert [u for u, _ in agent2._pending] == order_before
        # chip-demand cache rebuilt too (all plain jobs -> demand 1)
        assert [d for _, d in agent2._pending] == [1] * len(uuids)
        # watermark cleared: the first walk recomputes from scratch
        assert agent2._block_watermark is None
        assert agent2._pending_fresh

    def test_resync_is_one_scan_plus_one_listing(self, tmp_path):
        """The rebuild reads O(non-terminal) run rows in ONE paginated
        created_at ASC scan — not one scan per status bucket."""
        store = Store(":memory:")
        agent1 = LocalAgent(store, str(tmp_path), max_parallel=0)
        for i in range(30):
            store.create_run("p", spec=self.NOOP, name=f"q{i}")
        for _ in range(8):
            with agent1._dirty_lock:
                dirty, agent1._dirty = agent1._dirty, set()
            if not dirty:
                break
            agent1._tick_dirty(dirty)
        agent2 = LocalAgent(store, str(tmp_path), max_parallel=0)
        store.stats["runs_deserialized"] = 0
        agent2.cold_start_resync()
        # 30 queued rows, one page; a per-status implementation would
        # still pass this bound, but a per-run one (N get_run calls on
        # top) would not
        assert store.stats["runs_deserialized"] <= 35, store.stats


# ---------------------------------------------------------------------------
# graceful drain + the lease over the API
# ---------------------------------------------------------------------------


class TestGracefulDrain:
    def test_drain_releases_lease_for_instant_successor(self, tmp_path):
        store = Store(":memory:")
        agent1 = LocalAgent(store, str(tmp_path), poll_interval=0.05,
                            lease_ttl=30.0)
        agent1.start()
        try:
            assert store.get_lease("scheduler")["holder"] == agent1._lease_id
        finally:
            agent1.drain()
        # released, not expired-out: the row is GONE
        assert store.get_lease("scheduler") is None
        # successor acquires on start() without waiting out any TTL
        agent2 = LocalAgent(store, str(tmp_path), poll_interval=0.05,
                            lease_ttl=30.0)
        agent2.start()
        try:
            assert agent2.lease is not None
            assert store.get_lease("scheduler")["holder"] == agent2._lease_id
        finally:
            agent2.stop()

    def test_lease_visible_over_api(self, tmp_path):
        from polyaxon_tpu.api.server import ApiServer
        from polyaxon_tpu.client import AgentClient

        srv = ApiServer(artifacts_root=str(tmp_path / "a"), port=0).start()
        try:
            client = AgentClient(host=srv.url)
            assert client.lease() is None
            agent = LocalAgent(srv.store, str(tmp_path / "a"),
                               poll_interval=0.05)
            agent.start()
            try:
                lease = client.lease()
                assert lease["holder"] == agent._lease_id
                assert lease["expired"] is False
            finally:
                agent.stop()
            assert client.lease() is None  # released on stop
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# the fast agent-kill smoke (tier-1 stand-in for the slow soak)
# ---------------------------------------------------------------------------


class TestAgentKillSmoke:
    def test_kill_mid_wave_then_successor_converges(self, tmp_path):
        """Scaled-down kill-the-agent soak: SIGKILL (simulated) mid-wave,
        a successor takes over by TTL expiry, every run converges, zero
        duplicate pod launches, and the dead incarnation's late write is
        fenced off (>=1 rejection exercised)."""
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".c"))
        agent1 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05,
                            lease_ttl=0.6)
        agent1.start()
        uuids = [store.create_run(
            "p", spec=_job_spec(f"w{i}", sleep=1.5), name=f"w{i}")["uuid"]
            for i in range(3)]
        _wait(lambda: any(store.get_run(u)["status"] == "running"
                          for u in uuids),
              timeout=30, msg="wave mid-flight")

        agent1.hard_kill()
        # a surviving thread of the dead incarnation tries to write (an
        # executor callback would do exactly this): fenced off
        with pytest.raises(StaleLeaseError):
            agent1.store.transition(uuids[0], "stopping")
        assert store.stats["fence_rejections"] >= 1

        agent2 = LocalAgent(store, str(tmp_path), backend="cluster",
                            cluster=cluster, poll_interval=0.05,
                            lease_ttl=0.6)
        agent2.start()  # standby until agent1's TTL expires, then resync
        try:
            _wait(lambda: all(store.get_run(u)["status"] in
                              ("succeeded", "failed", "stopped")
                              for u in uuids),
                  timeout=25, msg="wave terminal after takeover")
            statuses = {store.get_run(u)["name"]: store.get_run(u)["status"]
                        for u in uuids}
            assert statuses == {f"w{i}": "succeeded" for i in range(3)}, (
                statuses, {u: store.get_statuses(u) for u in uuids})
            assert cluster.duplicate_applies == []
            # in-flight pods were adopted or intent-replayed — never
            # double-launched while live
            for u in uuids:
                assert cluster.launch_counts.get(u, 0) >= 1
        finally:
            agent2.stop()

    def test_demoted_agent_writes_stay_fenced_not_unfenced(self, tmp_path):
        """Organic demotion (rejected renewal / fenced-out write) must
        POISON the fence, not clear it: a cleared fence would downgrade
        the stale incarnation's surviving threads (executor callbacks,
        sidecar output merges) to UNFENCED writes that land — the exact
        mutation fencing exists to keep out."""
        store = Store(":memory:")
        agent = LocalAgent(store, str(tmp_path), poll_interval=0.05,
                           lease_ttl=30.0)
        assert agent._try_acquire_lease()
        r = store.create_run("p", spec={}, name="x")
        agent._on_stale_lease()  # what a StaleLeaseError write triggers
        assert agent.lease is None
        # poison fence: the REAL lease name with an impossible token
        # (tokens start at 1), so the store rejects it AND the rejection
        # routes back to the already-demoted lease, never a healthy one
        assert agent._current_fence() == ("scheduler", -1)
        with pytest.raises(StaleLeaseError):
            agent.store.transition(r["uuid"], "compiled")
        assert store.get_run(r["uuid"])["status"] == "created"
        # ...but a legitimate RE-acquisition (the standby hot-spare
        # becoming successor) lifts the poison: writes carry the new token
        assert agent._try_acquire_lease()
        _, changed = agent.store.transition(r["uuid"], "compiled")
        assert changed

    def test_split_brain_loser_demotes(self, tmp_path):
        """Two LIVE agents (GC-pause split-brain): the paused incumbent
        resumes after a takeover, its renewal is rejected, and it demotes
        to standby without having mutated anything."""
        store = Store(":memory:")
        agent1 = LocalAgent(store, str(tmp_path / "a1"), poll_interval=0.05,
                            lease_ttl=0.5)
        agent1.start()
        assert agent1.lease is not None
        agent1.suspend()  # GC pause: renewals stop
        time.sleep(0.8)   # TTL expires

        agent2 = LocalAgent(store, str(tmp_path / "a2"), poll_interval=0.05,
                            lease_ttl=0.5)
        agent2.start()
        try:
            _wait(lambda: agent2.lease is not None, timeout=10,
                  msg="successor acquires expired lease")
            token2 = agent2.lease["token"]
            # the incumbent wakes up...
            agent1.resume()
            _wait(lambda: agent1.lease is None, timeout=10,
                  msg="incumbent demotes")
            # ...and any write it still had in flight is fenced off
            r = store.create_run("p", spec={}, name="x")
            stale = FencedStore(store, lambda: ("scheduler", token2 - 1))
            with pytest.raises(StaleLeaseError):
                stale.transition(r["uuid"], "compiled")
            assert store.stats["fence_rejections"] >= 1
            # the winner still holds an un-bumped lease
            assert store.get_lease("scheduler")["holder"] == agent2._lease_id
            assert agent2.lease["token"] == token2
        finally:
            agent1.stop()
            agent2.stop()


# ---------------------------------------------------------------------------
# atomic checkpoints: checksum manifests + torn-step fallback
# ---------------------------------------------------------------------------


class TestCheckpointManifests:
    def _ckpt(self, tmp_path):
        from polyaxon_tpu.train.checkpoint import CheckpointConfig, Checkpointer

        return Checkpointer(CheckpointConfig(
            directory=str(tmp_path / "ck"), save_interval_steps=1,
            max_to_keep=5, async_save=False))

    @staticmethod
    def _state(step):
        import jax.numpy as jnp

        return {"w": jnp.arange(8, dtype=jnp.float32) * step,
                "step": jnp.asarray(step)}

    def _save_steps(self, ck, steps):
        for s in steps:
            assert ck.maybe_save(s, self._state(s), force=True)
        ck.wait()

    @staticmethod
    def _tear(ck, step):
        """Truncate the largest payload file of a step — a torn write."""
        root = ck._step_dir(step)
        largest, size = None, -1
        for dirpath, _, names in os.walk(root):
            for n in names:
                p = os.path.join(dirpath, n)
                if os.path.getsize(p) > size:
                    largest, size = p, os.path.getsize(p)
        assert largest is not None
        with open(largest, "r+b") as f:
            f.truncate(max(size // 2, 1))
        return largest

    def test_every_save_gets_a_verified_manifest(self, tmp_path):
        ck = self._ckpt(tmp_path)
        self._save_steps(ck, [1, 2, 3])
        for s in (1, 2, 3):
            assert os.path.exists(ck._manifest_path(s))
            assert ck.verify_step(s)
        assert ck.latest_complete_step() == 3

    def test_torn_latest_step_falls_back_to_newest_complete(self, tmp_path):
        ck = self._ckpt(tmp_path)
        self._save_steps(ck, [1, 2, 3])
        self._tear(ck, 3)
        assert not ck.verify_step(3)
        assert ck.latest_complete_step() == 2
        restored, step = ck.restore(self._state(0))
        assert step == 2
        assert float(restored["w"][1]) == 2.0  # step-2 payload, not garbage

    def test_all_steps_torn_raises_filenotfound(self, tmp_path):
        ck = self._ckpt(tmp_path)
        self._save_steps(ck, [1, 2])
        self._tear(ck, 1)
        self._tear(ck, 2)
        with pytest.raises(FileNotFoundError):
            ck.restore(self._state(0))

    def test_legacy_dir_without_manifests_still_restores(self, tmp_path):
        ck = self._ckpt(tmp_path)
        self._save_steps(ck, [1, 2])
        for s in (1, 2):
            os.unlink(ck._manifest_path(s))
        # pre-manifest checkpoints: trust orbax's atomic publish
        assert ck.complete_steps_desc() == [2, 1]
        _, step = ck.restore(self._state(0))
        assert step == 2

    def test_crash_before_manifest_flush_backfills_not_purges(self, tmp_path):
        """SIGKILL between an async Orbax finalize and the manifest
        flush: the step dir is complete but unmanifested. The restarted
        process must backfill the manifest (the dir's presence IS save
        completion) and resume from it — not mistake it for torn and
        delete 100 steps of progress."""
        ck = self._ckpt(tmp_path)
        self._save_steps(ck, [1, 2, 3])
        os.unlink(ck._manifest_path(3))  # the crash ate the flush

        ck2 = self._ckpt(tmp_path)  # restarted process, empty in-memory state
        assert ck2.complete_steps_desc() == [3, 2, 1]
        assert os.path.exists(ck2._manifest_path(3))  # backfilled
        restored, step = ck2.restore(self._state(0))
        assert step == 3
        assert float(restored["w"][1]) == 3.0
        assert os.path.isdir(ck2._step_dir(3))  # never purged

    def test_unproven_torn_step_quarantined_not_destroyed(self, tmp_path):
        """A newer step that fails the Orbax read while its bytes were
        never shown bad (manifest verifies / backfilled over the fault)
        is moved aside as quarantine-<step>, not irreversibly deleted —
        while still clearing the step number for the resumed run."""
        ck = self._ckpt(tmp_path)
        self._save_steps(ck, [1, 2])
        self._tear(ck, 2)
        os.unlink(ck._manifest_path(2))  # tear predates any manifest
        ck2 = self._ckpt(tmp_path)
        # backfill blesses the torn bytes; Orbax is the safety net
        restored, step = ck2.restore(self._state(0))
        assert step == 1
        q = os.path.join(ck2.directory, "quarantine-2")
        assert os.path.isdir(q)  # bytes preserved for hand recovery
        assert not os.path.isdir(ck2._step_dir(2))  # step number freed
        assert ck2.latest_step() == 1

    def test_manifest_gc_follows_max_to_keep(self, tmp_path):
        from polyaxon_tpu.train.checkpoint import CheckpointConfig, Checkpointer

        ck = Checkpointer(CheckpointConfig(
            directory=str(tmp_path / "ck"), save_interval_steps=1,
            max_to_keep=2, async_save=False))
        self._save_steps(ck, [1, 2, 3, 4])
        live = sorted(ck.manager.all_steps())
        manifests = sorted(
            int(n[len("manifest-"):-len(".json")])
            for n in os.listdir(ck.directory) if n.startswith("manifest-"))
        assert manifests == live


# ---------------------------------------------------------------------------
# ISSUE 6: horizontally sharded control plane — shard hashing, batch lease
# verbs, per-shard fencing, shard adoption, shard-scoped reaping
# ---------------------------------------------------------------------------


class TestShardHashing:
    def test_shard_index_stable_and_in_range(self):
        from polyaxon_tpu.api.store import shard_index, shard_lease_names

        uuids = [f"run-{i:04d}" for i in range(64)]
        first = [shard_index(u, 8) for u in uuids]
        # stability is load-bearing: every agent/incarnation must agree
        assert first == [shard_index(u, 8) for u in uuids]
        assert all(0 <= s < 8 for s in first)
        # crc32 spreads: a 64-run burst never collapses onto one shard
        assert len(set(first)) > 1
        assert shard_lease_names(3) == ["shard-0", "shard-1", "shard-2"]
        # degenerate K never divides by zero or escapes range
        assert shard_index("x", 0) == 0
        assert shard_lease_names(0) == ["shard-0"]

    def test_shard_index_independent_of_other_shard_count_only(self):
        from polyaxon_tpu.api.store import shard_index

        # same uuid, same K -> same shard across *processes* (pure fn of
        # bytes, no per-process salt)
        assert shard_index("abc", 8) == shard_index("abc", 8)


class TestBatchLeaseVerbs:
    def test_renew_leases_batch_per_entry_result(self):
        store = Store(":memory:")
        l0 = store.acquire_lease("shard-0", "a1", ttl=30)
        l1 = store.acquire_lease("shard-1", "a1", ttl=30)
        # shard-1 is stolen (release + fresh acquisition bumps its token)
        store.release_lease("shard-1", "a1", l1["token"])
        store.acquire_lease("shard-1", "a2", ttl=30)
        oks = store.renew_leases(
            [("shard-0", l0["token"]), ("shard-1", l1["token"])], "a1")
        # per-entry verdict: the stolen shard demotes ALONE
        assert oks == [True, False]

    def test_list_leases_prefix_and_expired_flag(self):
        store = Store(":memory:")
        store.acquire_lease("shard-0", "a1", ttl=30)
        store.acquire_lease("shard-1", "a1", ttl=0.01)
        store.acquire_lease("agent-xyz", "a1", ttl=30)
        time.sleep(0.05)
        shards = store.list_leases("shard-")
        assert [r["name"] for r in shards] == ["shard-0", "shard-1"]
        assert [r["expired"] for r in shards] == [False, True]
        every = store.list_leases()
        assert {r["name"] for r in every} == {"shard-0", "shard-1",
                                              "agent-xyz"}


class TestPerShardFencing:
    """Satellite 5: a fence rejection from a concurrent shard owner must
    reject only that shard's sub-batch, not abort the whole batch."""

    def _runs_spanning_two_shards(self, store, min_per_shard=2):
        from polyaxon_tpu.api.store import shard_index

        by_shard = {0: [], 1: []}
        while (len(by_shard[0]) < min_per_shard
               or len(by_shard[1]) < min_per_shard):
            r = store.create_run("p", spec={}, name=f"r{sum(map(len, by_shard.values()))}")
            by_shard[shard_index(r["uuid"], 2)].append(r["uuid"])
        return by_shard

    def test_transition_many_rejects_only_the_stale_shards_sub_batch(self):
        from polyaxon_tpu.api.store import shard_index

        store = Store(":memory:")
        by_shard = self._runs_spanning_two_shards(store)
        tokens = {
            "shard-0": store.acquire_lease("shard-0", "a1", ttl=30)["token"],
            "shard-1": store.acquire_lease("shard-1", "a1", ttl=30)["token"],
        }
        # a concurrent owner steals shard-1: a1's token for it is stale
        store.release_lease("shard-1", "a1", tokens["shard-1"])
        store.acquire_lease("shard-1", "a2", ttl=30)

        def _fence_for(uuid):
            shard = f"shard-{shard_index(uuid, 2)}"
            return (shard, tokens[shard])

        stale_names = []
        fenced = FencedStore(store, lambda: _fence_for,
                             on_stale=stale_names.append)
        # interleave the shards so the split is by FENCE, not by position
        batch = []
        for pair in zip(by_shard[0], by_shard[1]):
            batch.extend(pair)
        out = fenced.transition_many([(u, "compiled") for u in batch])
        assert len(out) == len(batch)
        for uuid, (row, changed) in zip(batch, out):
            if shard_index(uuid, 2) == 0:  # healthy shard: committed
                assert changed is True
                assert store.get_run(uuid)["status"] == "compiled"
            else:                          # stolen shard: rejected alone
                assert changed is False
                assert store.get_run(uuid)["status"] == "created"
        # one rejection for the one stale sub-batch, naming its shard
        assert stale_names == ["shard-1"]
        assert store.stats["fence_rejections"] == 1
        # ...and the per-lease labeled family recorded WHICH shard
        text = store.metrics.render()
        assert ('polyaxon_store_fence_rejections_by_lease_total'
                '{lease="shard-1"} 1') in text

    def test_single_run_writes_resolve_their_own_shard_fence(self):
        from polyaxon_tpu.api.store import shard_index

        store = Store(":memory:")
        by_shard = self._runs_spanning_two_shards(store, min_per_shard=1)
        tokens = {
            "shard-0": store.acquire_lease("shard-0", "a1", ttl=30)["token"],
            "shard-1": store.acquire_lease("shard-1", "a1", ttl=30)["token"],
        }
        store.release_lease("shard-1", "a1", tokens["shard-1"])
        store.acquire_lease("shard-1", "a2", ttl=30)

        def _fence_for(uuid):
            shard = f"shard-{shard_index(uuid, 2)}"
            return (shard, tokens[shard])

        fenced = FencedStore(store, lambda: _fence_for)
        ok_uuid, stale_uuid = by_shard[0][0], by_shard[1][0]
        fenced.transition(ok_uuid, "compiled")
        assert store.get_run(ok_uuid)["status"] == "compiled"
        with pytest.raises(StaleLeaseError):
            fenced.transition(stale_uuid, "compiled")
        assert store.get_run(stale_uuid)["status"] == "created"


class TestShardAdoption:
    def test_fleet_splits_shards_and_survivor_adopts_orphans(self, tmp_path):
        """Fast tier-1 smoke of the slow rolling-kill soak: two agents
        split 4 shards fair-share; killing one orphans its shards, which
        the survivor must adopt (the <2xTTL bound is asserted by the slow
        soak — here only liveness, load-tolerant)."""
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".cluster"))
        ttl = 0.5
        mk = lambda: LocalAgent(
            store, str(tmp_path), backend="cluster", cluster=cluster,
            poll_interval=0.05, lease_ttl=ttl, num_shards=4).start()
        a1, a2 = mk(), mk()
        try:
            _wait(lambda: a1._shard_leases and a2._shard_leases,
                  timeout=15, msg="fleet to split the shard space")
            held = lambda a: set(a._shard_leases)
            assert held(a1).isdisjoint(held(a2))
            _wait(lambda: len(held(a1) | held(a2)) == 4,
                  timeout=15, msg="all 4 shards owned")
            a1.hard_kill()
            orphaned = held(a1)
            _wait(lambda: orphaned <= held(a2), timeout=15,
                  msg="survivor to adopt the orphaned shards")
            rows = {r["name"]: r for r in store.list_leases("shard-")}
            assert all(rows[s]["holder"] == a2._lease_id
                       and not rows[s]["expired"] for s in orphaned)
        finally:
            a2.stop()

    def test_scheduling_respects_shard_ownership(self, tmp_path):
        """Each agent drives ONLY runs hashing into its shards: with two
        agents splitting the space, every run still reaches terminal (no
        run is orphaned by partitioning) and each launch intent names the
        shard lease that authorized it."""
        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".cluster"))
        mk = lambda: LocalAgent(
            store, str(tmp_path), backend="cluster", cluster=cluster,
            poll_interval=0.05, lease_ttl=1.0, num_shards=4,
            max_parallel=4).start()
        a1, a2 = mk(), mk()
        try:
            _wait(lambda: a1._shard_leases and a2._shard_leases,
                  timeout=15, msg="fleet to split the shard space")
            uuids = [store.create_run("p", spec=_job_spec(f"j{i}"),
                                      name=f"j{i}")["uuid"]
                     for i in range(6)]
            _wait(lambda: all(
                store.get_run(u)["status"] in ("succeeded", "failed")
                for u in uuids), timeout=60, msg="wave to finish")
            assert all(store.get_run(u)["status"] == "succeeded"
                       for u in uuids)
            from polyaxon_tpu.api.store import shard_index

            for u in uuids:
                intent = store.get_launch_intent(u)
                assert intent is not None
                assert intent["lease_name"] == f"shard-{shard_index(u, 4)}"
        finally:
            a1.drain()
            a2.stop()


class TestShardScopedReaper:
    def _zombie_run(self, store, name):
        spec = {"kind": "operation",
                "component": {"kind": "component",
                              "run": {"kind": "job", "container": {
                                  "command": [sys.executable, "-c",
                                              "pass"]}}}}
        run = store.create_run("p", spec=spec, name=name)
        store.transition(run["uuid"], "running", force=True)
        return run["uuid"]

    def test_two_reapers_reap_disjoint_shards_exactly_once(self):
        """Satellite 1: N agents never double-reap one run — each reaper
        only strikes runs of its own shards, and the reap counters sum to
        exactly one action per zombie across the fleet."""
        from polyaxon_tpu.api.store import shard_index
        from polyaxon_tpu.resilience.heartbeat import ZombieReaper

        store = Store(":memory:")
        uuids = [self._zombie_run(store, f"z{i}") for i in range(4)]
        reapers = [
            ZombieReaper(store, owned=set, zombie_after=0.05,
                         metrics=store.metrics,
                         owns_run=lambda u, s=s: shard_index(u, 2) == s)
            for s in (0, 1)
        ]
        time.sleep(0.1)
        for r in reapers:
            assert r.pass_once() == []  # strike one each, scoped
            r._last_pass = float("-inf")
        actions = [r.pass_once() for r in reapers]
        reaped = [u for acts in actions for u, _ in acts]
        # exactly-once across the fleet: every zombie reaped by exactly
        # its shard's owner, none twice
        assert sorted(reaped) == sorted(uuids)
        for r, acts in zip(reapers, actions):
            for u, _ in acts:
                assert r.owns_run(u)
        # the shared counter family agrees (scrape == audit trail)
        text = store.metrics.render()
        total = sum(
            float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("polyaxon_reaper_reaps_total"))
        assert total == len(uuids)

    def test_racing_reap_counts_nobody(self):
        """A reap that lost a race (the run moved between the reaper's
        LISTING and its strike) is counted by NOBODY: the transition's
        changed=False result guards the counter. The stale listing is
        pinned via the list_runs hook — exactly what a second agent's
        concurrent terminal write looks like to a mid-pass reaper."""
        from polyaxon_tpu.resilience.heartbeat import ZombieReaper

        store = Store(":memory:")
        uuid = self._zombie_run(store, "z")
        stale_row = dict(store.get_run(uuid))  # snapshot: still 'running'
        reaper = ZombieReaper(
            store, owned=set, zombie_after=0.05, metrics=store.metrics,
            list_runs=lambda status: (
                [stale_row] if status == "running" else []))
        time.sleep(0.1)
        assert reaper.pass_once() == []  # strike one
        # another writer (the run's own pod) finishes it first; the
        # reaper's next pass still sees the stale listing and strikes
        store.transition(uuid, "succeeded")
        reaper._last_pass = float("-inf")
        assert reaper.pass_once() == []  # reap attempted, lost, uncounted
        assert store.get_run(uuid)["status"] == "succeeded"
        for line in store.metrics.render().splitlines():
            if line.startswith("polyaxon_reaper_reaps_total"):
                assert line.endswith(" 0"), line


class TestShardConfigAgreement:
    def test_mismatched_num_shards_adopts_the_fleets_layout(self, tmp_path):
        """Two agents hashing the run space with different K would BOTH
        own some runs under VALID fences — duplicate launches the
        per-shard fencing cannot catch. The first starter pins K in
        control_config (first-writer-wins); a mismatched later starter
        adopts it before probing for shards."""
        from polyaxon_tpu.api.store import shard_lease_names

        store = Store(":memory:")
        cluster = FakeCluster(str(tmp_path / ".cluster"))
        a1 = LocalAgent(store, str(tmp_path), backend="cluster",
                        cluster=cluster, poll_interval=0.05,
                        lease_ttl=2.0, num_shards=8).start()
        a2 = LocalAgent(store, str(tmp_path), backend="cluster",
                        cluster=cluster, poll_interval=0.05,
                        lease_ttl=2.0, num_shards=16).start()
        try:
            assert store.get_config("num_shards") == "8"
            assert a2.num_shards == 8
            assert a2.shards == shard_lease_names(8)
            # and the adopted layout is what it probes/acquires with
            _wait(lambda: a2._shard_leases, timeout=15,
                  msg="mismatched starter to join the 8-shard fleet")
            assert set(a2._shard_leases) <= set(shard_lease_names(8))
        finally:
            a1.drain()
            a2.stop()

    def test_claim_config_is_first_writer_wins(self):
        store = Store(":memory:")
        assert store.claim_config("num_shards", "8") == "8"
        assert store.claim_config("num_shards", "16") == "8"
        assert store.get_config("num_shards") == "8"
        assert store.get_config("missing") is None
        # operator override (whole-fleet restart): set_config re-pins
        store.set_config("num_shards", "16")
        assert store.claim_config("num_shards", "4") == "16"


class TestPresenceGC:
    def test_probe_purges_dead_incarnations_presence_rows(self, tmp_path):
        """Crashed incarnations never DELETE their self-named agent-*
        presence row; the survivors' probes must GC the expired ones or
        agent_leases grows by a row per crash forever."""
        from polyaxon_tpu.api.store import AGENT_PREFIX

        store = Store(":memory:")
        for i in range(5):  # five crashed incarnations
            store.acquire_lease(f"{AGENT_PREFIX}dead{i}", f"dead{i}",
                                ttl=0.01)
        time.sleep(0.05)
        cluster = FakeCluster(str(tmp_path / ".cluster"))
        agent = LocalAgent(store, str(tmp_path), backend="cluster",
                           cluster=cluster, poll_interval=0.05,
                           lease_ttl=2.0, num_shards=2).start()
        try:
            _wait(lambda: not [
                r for r in store.list_leases(AGENT_PREFIX)
                if r["holder"].startswith("dead")],
                timeout=15, msg="probe to GC dead presence rows")
            live = store.list_leases(AGENT_PREFIX)
            assert [r["holder"] for r in live] == [agent._lease_id]
        finally:
            agent.stop()
