"""Partition-rule engine + checkpoint import + LoRA (ISSUE 13).

Tier-1 coverage: engine semantics (first-match-wins, scalar
auto-replicate, loud UnmatchedParamError), built-in rule-set parity with
the legacy logical-axis specs for EVERY zoo model, polyaxonfile rule
parsing + compile-time validation, foreign-checkpoint import (flat +
HF-llama layouts) to fp32-forward parity on a real mesh, LoRA training
that only moves adapters, and the plan/audit tooling. The 2-process
multislice import e2e is slow-marked (out of the 870s window)."""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from polyaxon_tpu.models import REGISTRY, llama, transformer
from polyaxon_tpu.parallel import ShardingRules, build_mesh
from polyaxon_tpu.partition import (
    RuleSyntaxError,
    UnmatchedParamError,
    abstract_params_for,
    audit,
    build_plan,
    match_partition_rules,
    overlay_partition_rules,
    parse_rules,
    rules_for,
    specs_equivalent,
    tree_paths,
    validate_rules_against,
)
from polyaxon_tpu.partition import convert
from polyaxon_tpu.partition.lora import (
    LoRAConfig,
    LoRATask,
    LoRATargetError,
    frozen_base_optimizer,
    init_lora,
    merge_lora,
)
from polyaxon_tpu.train.tasks import task_for


def _tree(**leaves):
    """name -> shape dict into a flat abstract tree."""
    return {k: jax.ShapeDtypeStruct(v, jnp.float32) for k, v in leaves.items()}


class TestEngine:
    def test_first_match_wins(self):
        tree = {"a": {"w": jax.ShapeDtypeStruct((4, 4), jnp.float32)}}
        rules = (("a/w$", P("model", None)), ("w$", P(None, "model")))
        specs = match_partition_rules(rules, tree)
        assert specs["a"]["w"] == P("model", None)
        # order flipped -> other rule wins
        specs = match_partition_rules(tuple(reversed(rules)), tree)
        assert specs["a"]["w"] == P(None, "model")

    def test_search_semantics_match_nested_paths(self):
        tree = {"enc": {"layers": {"attn": {"wq": jax.ShapeDtypeStruct(
            (2, 4, 4), jnp.float32)}}}}
        specs = match_partition_rules((("attn/wq$", P(None, "fsdp", "model")),),
                                      tree)
        assert specs["enc"]["layers"]["attn"]["wq"] == P(None, "fsdp", "model")

    def test_scalar_auto_replicates_without_rule(self):
        tree = {"step": jax.ShapeDtypeStruct((), jnp.int32),
                "one": jax.ShapeDtypeStruct((1,), jnp.float32),
                "w": jax.ShapeDtypeStruct((4, 4), jnp.float32)}
        specs = match_partition_rules((("w$", P("model")),), tree)
        assert specs["step"] == P()
        assert specs["one"] == P()
        assert specs["w"] == P("model")

    def test_unmatched_lists_every_path(self):
        tree = _tree(a=(4, 4), b=(8,), c=(2, 2))
        with pytest.raises(UnmatchedParamError) as ei:
            match_partition_rules((("^a$", P()),), tree)
        assert sorted(ei.value.paths) == ["b", "c"]
        assert "b" in str(ei.value) and "c" in str(ei.value)

    def test_bad_regex_raises_rule_syntax_with_pattern(self):
        with pytest.raises(RuleSyntaxError) as ei:
            match_partition_rules((("a/(w$", P()),), _tree(a=(2,)))
        assert "a/(w$" in str(ei.value)
        assert ei.value.rule == "a/(w$"

    def test_overlong_spec_rejected(self):
        with pytest.raises(RuleSyntaxError) as ei:
            match_partition_rules(
                (("w$", P("model", None, "fsdp")),), _tree(w=(4, 4)))
        assert "3-entry" in str(ei.value)

    def test_short_spec_accepted(self):
        # JAX pads unspecified trailing dims with None
        specs = match_partition_rules((("w$", P("fsdp")),), _tree(w=(4, 4, 4)))
        assert specs["w"] == P("fsdp")

    def test_overlay_overrides_and_keeps_base(self):
        tree = _tree(a=(4, 4), b=(4, 4))
        base = {"a": P("fsdp"), "b": P("model")}
        out = overlay_partition_rules((("^a$", P()),), tree, base)
        assert out["a"] == P() and out["b"] == P("model")


class TestParseRules:
    def test_parse_forms(self):
        rules = parse_rules([
            ["norm", None],
            ["bias$", "replicated"],
            ["wq$", [None, "fsdp", ["data", "expert"]]],
        ])
        assert rules[0] == ("norm", P())
        assert rules[1] == ("bias$", P())
        assert rules[2] == ("wq$", P(None, "fsdp", ("data", "expert")))

    def test_parse_idempotent(self):
        rules = parse_rules([["wq$", ["model"]]])
        assert parse_rules(rules) == rules

    def test_unknown_axis_rejected(self):
        with pytest.raises(RuleSyntaxError) as ei:
            parse_rules([["wq$", ["tensor"]]])
        assert "tensor" in str(ei.value) and "wq$" in str(ei.value)

    def test_non_pair_rejected(self):
        with pytest.raises(RuleSyntaxError):
            parse_rules([["only-a-pattern"]])
        with pytest.raises(RuleSyntaxError):
            parse_rules("attn: model")

    def test_validate_no_match_names_nearest_paths(self):
        tree = abstract_params_for("llama-tiny")
        with pytest.raises(RuleSyntaxError) as ei:
            validate_rules_against(parse_rules([["attn/wz$", None]]),
                                   tree_paths(tree))
        msg = str(ei.value)
        assert "matches no parameter" in msg
        assert "attn/w" in msg  # nearest real paths are suggested


class TestBuiltinParity:
    """The acceptance bar: every shipped rule set reproduces the legacy
    logical-axis ShardingRules specs EXACTLY, per model."""

    @pytest.mark.parametrize("name", sorted(REGISTRY))
    def test_engine_matches_legacy_specs(self, name):
        family, cfg = REGISTRY[name]
        abstract = abstract_params_for(name)
        engine = match_partition_rules(rules_for(name), abstract)
        kwargs = {"image_size": 32} if family == "resnet" else {}
        oracle = task_for(family, cfg, **kwargs).param_specs(ShardingRules())

        def is_spec(x):
            return isinstance(x, P)

        engine_flat = tree_paths(engine, is_leaf=is_spec)
        oracle_flat = tree_paths(oracle, is_leaf=is_spec)
        assert [p for p, _ in engine_flat] == [p for p, _ in oracle_flat]
        for (path, got), (_, want) in zip(engine_flat, oracle_flat):
            assert specs_equivalent(got, want), (
                f"{name}: {path}: engine {got} != legacy {want}")

    def test_audit_clean(self):
        report = audit(["llama-tiny", "llama-moe-tiny", "gpt2-tiny",
                        "bert-tiny", "vit-tiny", "resnet18-cifar"])
        assert all(r["status"] == "ok" for r in report.values())

    def test_audit_catches_model_edit_drift(self, monkeypatch):
        """A new param name that no rule matches must fail the audit loudly
        (the 'model edits can't silently fall back to replicated' lint)."""
        from polyaxon_tpu.partition import builtins as pb

        orig = pb.abstract_params_for_config

        def with_extra(family, cfg):
            tree = orig(family, cfg)
            if family == "lm":
                tree = dict(tree)
                tree["brand_new_block"] = {
                    "w": jax.ShapeDtypeStruct((8, 8), jnp.float32)}
            return tree

        monkeypatch.setattr(pb, "abstract_params_for_config", with_extra)
        monkeypatch.setattr("polyaxon_tpu.partition.plan."
                            "abstract_params_for_config", with_extra)
        with pytest.raises(UnmatchedParamError) as ei:
            audit(["llama-tiny"])
        assert "brand_new_block/w" in str(ei.value)


class TestTrainerOverlay:
    def test_user_rules_override_builtin_shardings(self):
        from polyaxon_tpu.train import OptimizerConfig, Trainer, TrainerConfig

        cfg = llama.LLAMA_TINY
        mesh = build_mesh({"fsdp": 2, "model": 2})
        tcfg = TrainerConfig(model=cfg, optimizer=OptimizerConfig(),
                             batch_size=8, seq_len=16)
        tr = Trainer(tcfg, mesh=mesh,
                     partition_rules=[["attn/w[qkv]$",
                                       [None, None, "model", None]]])
        wq = tr.param_shardings["layers"]["attn"]["wq"]
        assert specs_equivalent(wq.spec, P(None, None, "model", None))
        # untouched params keep the built-in spec
        wo = tr.param_shardings["layers"]["attn"]["wo"]
        assert specs_equivalent(wo.spec, P(None, "model", None, "fsdp"))
        # opt-state moments inherit the overlay through _state_shardings
        state = tr.init_state()
        mu_wq = [leaf for path, leaf in tree_paths(state.opt_state)
                 if "mu" in path and path.endswith("attn/wq")]
        assert mu_wq, "adam mu for wq not found in opt state"
        assert specs_equivalent(mu_wq[0].sharding.spec,
                                P(None, None, "model", None))


@pytest.fixture(scope="module")
def tiny_native():
    cfg = llama.LLAMA_TINY
    params = transformer.init(jax.random.PRNGKey(3), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0,
                                cfg.vocab_size)
    ref = transformer.apply(params, tokens, cfg)
    return cfg, params, tokens, ref


class TestImport:
    def test_flat_roundtrip_forward_parity_and_shardings(
            self, tmp_path, tiny_native):
        cfg, params, tokens, ref = tiny_native
        convert.save_flat(params, str(tmp_path / "flat"))
        mesh = build_mesh({"fsdp": 2, "model": 2})
        imported = convert.import_params(
            str(tmp_path / "flat"), cfg, mesh, layout="flat")
        out = transformer.apply(imported, tokens, cfg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        wq = imported["layers"]["attn"]["wq"]
        assert specs_equivalent(wq.sharding.spec,
                                P(None, "fsdp", "model", None))
        # buffers land sharded: each addressable shard holds 1/4 of wq
        shard = wq.addressable_shards[0]
        assert shard.data.shape[1] == wq.shape[1] // 2
        assert shard.data.shape[2] == wq.shape[2] // 2

    def test_hf_llama_roundtrip_matches_native_forward(
            self, tmp_path, tiny_native):
        """Acceptance: a foreign HF-layout llama checkpoint imports through
        the rule engine and matches the native forward to fp32 tolerance."""
        cfg, params, tokens, ref = tiny_native
        convert.export_hf_llama(params, cfg, str(tmp_path / "hf"))
        # HF-layout keys exist exactly as HF names them
        src = convert.open_source(str(tmp_path / "hf"))
        assert "model.layers.0.self_attn.q_proj.weight" in src.keys()
        assert convert.detect_layout(src) == "hf-llama"
        mesh = build_mesh({"fsdp": 2, "model": 2})
        imported = convert.import_params(str(tmp_path / "hf"), cfg, mesh)
        out = transformer.apply(imported, tokens, cfg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_import_reads_only_shard_slices(self, tmp_path, tiny_native):
        """'Never materializes unsharded on one host': on a sharded mesh
        the per-shard callbacks ask the source for sub-slices, not the
        full stacked tensor."""
        cfg, params, _, _ = tiny_native
        convert.save_flat(params, str(tmp_path / "flat"))
        src = convert.open_source(str(tmp_path / "flat"))
        reads: list[tuple] = []
        orig_get = src.get

        class SpyArr:
            def __init__(self, arr, key):
                self.arr, self.key = arr, key
                self.shape, self.dtype = arr.shape, arr.dtype

            def transpose(self, axes):
                return SpyArr(self.arr.transpose(axes), self.key)

            def __getitem__(self, idx):
                reads.append((self.key, idx))
                return self.arr[idx]

        src.get = lambda name: SpyArr(orig_get(name), name)
        mesh = build_mesh({"fsdp": 2, "model": 2})
        convert.import_params(src, cfg, mesh, layout="flat")
        wq_reads = [idx for key, idx in reads if key == "layers/attn/wq"]
        assert wq_reads, "wq was never read"
        full = transformer.init(jax.random.PRNGKey(0), cfg)
        wq_shape = full["layers"]["attn"]["wq"].shape
        for idx in wq_reads:
            sliced = np.empty(wq_shape, np.int8)[idx]
            assert sliced.shape[1] <= wq_shape[1] // 2, (
                "a shard callback read the full embed dim")

    def test_dtype_cast_floats_only(self, tmp_path, tiny_native):
        cfg, params, _, _ = tiny_native
        convert.save_flat(params, str(tmp_path / "flat"))
        mesh = build_mesh({})
        imported = convert.import_params(
            str(tmp_path / "flat"), cfg, mesh, layout="flat",
            dtype="bfloat16")
        assert imported["layers"]["attn"]["wq"].dtype == jnp.bfloat16

    def test_missing_source_keys_listed(self, tmp_path, tiny_native):
        cfg, params, _, _ = tiny_native
        convert.save_flat(params, str(tmp_path / "flat"))
        os.unlink(tmp_path / "flat" / "embed" / "tokens.npy")
        mesh = build_mesh({})
        with pytest.raises(convert.ImportError_) as ei:
            convert.import_params(str(tmp_path / "flat"), cfg, mesh,
                                  layout="flat")
        assert "embed/tokens" in str(ei.value)

    def test_key_map_and_transpose(self, tmp_path, tiny_native):
        """The generic flat layout adapts renamed/transposed foreign trees
        without a bespoke layout class."""
        cfg, params, tokens, ref = tiny_native
        foreign = {p.replace("lm_head/w", "head/kernel"): v
                   for p, v in tree_paths(params)}
        foreign["head/kernel"] = np.asarray(foreign["head/kernel"]).T
        convert.save_flat(foreign, str(tmp_path / "foreign"))
        mesh = build_mesh({})
        imported = convert.import_params(
            str(tmp_path / "foreign"), cfg, mesh, layout="flat",
            key_map=[["^lm_head/w$", "head/kernel"]],
            transpose=[["^lm_head/w$", [1, 0]]])
        out = transformer.apply(imported, tokens, cfg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_hf_layout_rejects_non_llama_config(self, tmp_path):
        from polyaxon_tpu.models import gpt2

        with pytest.raises(convert.ImportError_) as ei:
            convert.hf_llama_entries(
                convert.NpyDirSource(str(tmp_path)), gpt2.GPT2_TINY, {})
        assert "not HF-llama-shaped" in str(ei.value)


class TestLoRA:
    def test_b_zero_init_means_identity_at_step0(self, tiny_native):
        cfg, params, tokens, ref = tiny_native
        lcfg = LoRAConfig(rank=4, alpha=8.0)
        adapters = init_lora(jax.random.PRNGKey(0), params, lcfg)
        merged = merge_lora(params, adapters, lcfg)
        out = transformer.apply(merged, tokens, cfg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6, rtol=1e-6)

    def test_merge_changes_targets_only(self, tiny_native):
        cfg, params, _, _ = tiny_native
        lcfg = LoRAConfig(rank=4, alpha=8.0, target=r"attn/wq$")
        adapters = init_lora(jax.random.PRNGKey(0), params, lcfg)
        adapters["layers"]["attn"]["wq"]["b"] = jnp.ones_like(
            adapters["layers"]["attn"]["wq"]["b"])
        merged = merge_lora(params, adapters, lcfg)
        assert not np.allclose(np.asarray(merged["layers"]["attn"]["wq"]),
                               np.asarray(params["layers"]["attn"]["wq"]))
        np.testing.assert_array_equal(
            np.asarray(merged["layers"]["attn"]["wk"]),
            np.asarray(params["layers"]["attn"]["wk"]))

    def test_bad_target_lists_nearest_paths(self, tiny_native):
        _, params, _, _ = tiny_native
        with pytest.raises(LoRATargetError) as ei:
            init_lora(jax.random.PRNGKey(0), params,
                      LoRAConfig(target=r"attn/wz$"))
        assert "matches no parameter" in str(ei.value)

    def test_unfactorable_target_rejected(self, tiny_native):
        _, params, _, _ = tiny_native
        with pytest.raises(LoRATargetError) as ei:
            init_lora(jax.random.PRNGKey(0), params,
                      LoRAConfig(target=r"attn_norm/scale$"))
        assert "no known fan-in/fan-out" in str(ei.value)

    def test_lora_run_trains_only_adapters(self, tiny_native):
        """Acceptance: a LoRA run trains ONLY adapter params — the base is
        bitwise frozen across optimizer steps while adapters move."""
        from polyaxon_tpu.train import (
            DataConfig, OptimizerConfig, Trainer, TrainerConfig,
            make_batches, make_optimizer,
        )
        from polyaxon_tpu.train.tasks import LMTask

        cfg = llama.LLAMA_TINY
        lcfg = LoRAConfig(rank=4, alpha=8.0)
        task = LoRATask(LMTask(cfg), lcfg)
        tcfg = TrainerConfig(
            model=cfg,
            optimizer=OptimizerConfig(learning_rate=1e-2, warmup_steps=1,
                                      total_steps=4),
            batch_size=8, seq_len=16, parallelism={"fsdp": 2, "model": 2})
        mesh = build_mesh(tcfg.parallelism)
        tr = Trainer(tcfg, mesh=mesh, task=task,
                     tx=frozen_base_optimizer(make_optimizer(tcfg.optimizer)))
        data = make_batches(DataConfig(
            kind="synthetic-lm", batch_size=8, seq_len=16,
            vocab_size=cfg.vocab_size), mesh)
        state, _ = tr.restore_or_init()
        base_before = jax.tree.map(np.asarray, state.params["base"])
        state, metrics = tr.fit(data, num_steps=4, state=state)
        assert np.isfinite(metrics["loss"])
        for (p, before), (_, after) in zip(
                tree_paths(base_before),
                tree_paths(jax.tree.map(np.asarray, state.params["base"]))):
            np.testing.assert_array_equal(before, after,
                                          err_msg=f"base param {p} moved")
        moved = [p for p, leaf in tree_paths(state.params["lora"])
                 if p.endswith("/b") and np.abs(np.asarray(leaf)).max() > 0]
        assert moved, "no adapter moved after 4 steps"

    def test_base_shards_adapters_replicate(self, tiny_native):
        from polyaxon_tpu.train import OptimizerConfig, Trainer, TrainerConfig
        from polyaxon_tpu.train.tasks import LMTask

        cfg = llama.LLAMA_TINY
        task = LoRATask(LMTask(cfg), LoRAConfig(rank=4))
        mesh = build_mesh({"fsdp": 2, "model": 2})
        tr = Trainer(TrainerConfig(model=cfg, optimizer=OptimizerConfig(),
                                   batch_size=8, seq_len=16),
                     mesh=mesh, task=task)
        wq = tr.param_shardings["base"]["layers"]["attn"]["wq"]
        assert specs_equivalent(wq.spec, P(None, "fsdp", "model", None))
        a = tr.param_shardings["lora"]["layers"]["attn"]["wq"]["a"]
        assert specs_equivalent(a.spec, P())


class TestCompileTime:
    """UnmatchedParamError / rule-syntax errors surface at COMPILE time
    (resolver -> _render_builtin -> validate_builtin_spec), not mid-init
    in the pod."""

    def _resolve(self, runtime, partition_rules=None):
        from polyaxon_tpu.compiler.resolver import resolve
        from polyaxon_tpu.polyaxonfile import check_polyaxonfile

        run = {"kind": "tpujob", "accelerator": "v5e", "topology": "2x2",
               "runtime": runtime}
        if partition_rules is not None:
            run["partitionRules"] = partition_rules
        spec = check_polyaxonfile({
            "kind": "operation", "name": "t",
            "component": {"kind": "component", "run": run}}).to_dict()
        return resolve(spec, run_uuid="u" * 32, project="p",
                       artifacts_path="/tmp/x")

    def test_bad_regex_fails_resolve_with_offending_pattern(self):
        with pytest.raises(RuleSyntaxError) as ei:
            self._resolve({"model": "llama-tiny",
                           "partition_rules": [["attn/(wq$", None]]})
        assert "attn/(wq$" in str(ei.value)

    def test_no_match_rule_fails_resolve_with_nearest_paths(self):
        with pytest.raises(RuleSyntaxError) as ei:
            self._resolve({"model": "llama-tiny",
                           "partition_rules": [["attn/wqq$", None]]})
        assert "nearest param paths" in str(ei.value)
        assert "attn/w" in str(ei.value)

    def test_unknown_axis_fails_resolve(self):
        with pytest.raises(RuleSyntaxError):
            self._resolve({"model": "llama-tiny",
                           "partition_rules": [["attn/wq$", ["tensor"]]]})

    def test_unknown_model_fails_resolve_when_partition_keys_present(self):
        with pytest.raises(RuleSyntaxError) as ei:
            self._resolve({"model": "llama-9t",
                           "partition_rules": [["attn/wq$", None]]})
        assert "llama-9t" in str(ei.value)

    def test_bad_lora_target_fails_resolve(self):
        with pytest.raises(LoRATargetError):
            self._resolve({"model": "llama-tiny",
                           "lora": {"rank": 4, "target": "attn/nope$"}})

    def test_bad_import_key_map_regex_fails_resolve(self):
        with pytest.raises(RuleSyntaxError) as ei:
            self._resolve({"model": "llama-tiny",
                           "import": {"path": "/x", "layout": "flat",
                                      "key_map": [["[", "x"]]}})
        assert "[" in str(ei.value) and "does not compile" in str(ei.value)

    def test_bad_import_dtype_fails_resolve(self):
        with pytest.raises(RuleSyntaxError) as ei:
            self._resolve({"model": "llama-tiny",
                           "import": {"path": "/x", "dtype": "bfloat17"}})
        assert "bfloat17" in str(ei.value)

    def test_bad_transpose_axes_fail_resolve(self):
        with pytest.raises(RuleSyntaxError):
            self._resolve({"model": "llama-tiny",
                           "import": {"path": "/x", "layout": "flat",
                                      "transpose": [["wq$", ["a", "b"]]]}})

    def test_valid_blocks_flow_into_payload(self):
        r = self._resolve(
            {"model": "llama-tiny", "lora": {"rank": 4}},
            partition_rules=[["attn/wq$", [None, "fsdp", "model", None]]])
        b = r.payload.builtin
        assert b["partition_rules"] == \
            [["attn/wq$", [None, "fsdp", "model", None]]]
        assert b["lora"] == {"rank": 4}
        assert b["num_slices"] == 1

    def test_runtime_dict_rules_win_over_run_level(self):
        r = self._resolve(
            {"model": "llama-tiny",
             "partition_rules": [["attn/wq$", None]]},
            partition_rules=[["mlp/wi$", None]])
        assert r.payload.builtin["partition_rules"] == [["attn/wq$", None]]

    def test_multislice_num_slices_injected(self):
        from polyaxon_tpu.compiler.resolver import resolve
        from polyaxon_tpu.polyaxonfile import check_polyaxonfile

        spec = check_polyaxonfile({
            "kind": "operation", "name": "t",
            "component": {"kind": "component", "run": {
                "kind": "tpujob", "accelerator": "v5e", "topology": "2x2",
                "numSlices": 2,
                "runtime": {"model": "llama-tiny"}}}}).to_dict()
        r = resolve(spec, run_uuid="u" * 32, project="p",
                    artifacts_path="/tmp/x")
        assert r.payload.builtin["num_slices"] == 2


class TestPlan:
    def test_plan_table_and_summary(self):
        plan = build_plan("llama-tiny", parallelism={"fsdp": 2, "model": 2},
                          num_devices=4)
        s = plan["summary"]
        assert s["num_params"] == llama.LLAMA_TINY.num_params()
        assert set(s["axes_used"]) == {"fsdp", "model"}
        by_param = {r["param"]: r for r in plan["rows"]}
        wq = by_param["layers/attn/wq"]
        assert wq["bytes_per_device"] == wq["bytes"] // 4
        # replicated leaves pay full bytes on every device
        norm = by_param["final_norm/scale"]
        assert norm["bytes_per_device"] == norm["bytes"]
        total = sum(r["bytes_per_device"] for r in plan["rows"])
        assert s["bytes_per_device"] == total

    def test_plan_absorbs_capacity_like_build_mesh(self):
        plan = build_plan("llama-tiny", parallelism={"model": 2},
                          num_devices=8)
        assert plan["summary"]["axis_sizes"] == {"data": 4, "model": 2}

    def test_plan_cli_runtime_num_slices_wins_over_topology(self, tmp_path):
        """Hand-built specs set num_slices in the runtime dict (the same
        precedence run_builtin honors); the plan must mirror it."""
        import yaml
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        f = tmp_path / "op.yaml"
        f.write_text(yaml.safe_dump({
            "version": 1.1, "kind": "component", "name": "p",
            "run": {"kind": "tpujob", "accelerator": "v5e",
                    "topology": "2x2",
                    "runtime": {"model": "llama-tiny", "num_slices": 2}}}))
        res = CliRunner().invoke(cli, ["partition", "plan", "-f", str(f),
                                       "--json"])
        assert res.exit_code == 0, res.output
        assert json.loads(res.output)["summary"]["num_slices"] == 2

    def test_plan_applies_user_rules_and_lora(self):
        plan = build_plan(
            "llama-tiny", parallelism={"fsdp": 2, "model": 2}, num_devices=4,
            partition_rules=[["attn/wq$", None]], lora={"rank": 4})
        by_param = {r["param"]: r for r in plan["rows"]}
        assert by_param["base/layers/attn/wq"]["spec"] == "replicated"
        assert "lora/layers/attn/wq/a" in by_param

    def test_format_plan_renders(self):
        from polyaxon_tpu.partition import format_plan

        text = format_plan(build_plan("llama-tiny"))
        assert "layers/attn/wq" in text and "bytes/device" in text

    def test_plan_cli_json(self, tmp_path):
        import yaml
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        f = tmp_path / "op.yaml"
        f.write_text(yaml.safe_dump({
            "version": 1.1, "kind": "component", "name": "p",
            "run": {"kind": "tpujob", "accelerator": "v5e",
                    "topology": "2x2",
                    "parallelism": {"fsdp": 2, "model": 2},
                    "runtime": {"model": "llama-tiny"}}}))
        res = CliRunner().invoke(cli, ["partition", "plan", "-f", str(f),
                                       "--json"])
        assert res.exit_code == 0, res.output
        plan = json.loads(res.output)
        assert plan["summary"]["num_devices"] == 4

    def test_plan_cli_rejects_containers(self, tmp_path):
        import yaml
        from click.testing import CliRunner

        from polyaxon_tpu.cli.main import cli

        f = tmp_path / "op.yaml"
        f.write_text(yaml.safe_dump({
            "version": 1.1, "kind": "component", "name": "p",
            "run": {"kind": "job",
                    "container": {"command": ["true"]}}}))
        res = CliRunner().invoke(cli, ["partition", "plan", "-f", str(f)])
        assert res.exit_code != 0
        assert "runtime" in res.output


class TestRuntimeIntegration:
    def test_builtin_runs_import_lora_rules(self, tmp_path, monkeypatch,
                                            tiny_native):
        """run_builtin with partition_rules + import + lora: trains, base
        == imported checkpoint (frozen), summary finite."""
        cfg, params, _, _ = tiny_native
        hf_dir = tmp_path / "ckpt"
        convert.export_hf_llama(params, cfg, str(hf_dir))
        from polyaxon_tpu.runtime.builtin import run_builtin

        monkeypatch.chdir(tmp_path)
        monkeypatch.setenv("PLX_ARTIFACTS_PATH", str(tmp_path))
        summary = run_builtin({
            "model": "llama-tiny", "steps": 2, "batch_size": 8,
            "seq_len": 16, "checkpoint": False, "watchdog": False,
            "parallelism": {"fsdp": 2, "model": 2},
            "partition_rules": [["attn/w[qkv]$",
                                 [None, None, "model", None]]],
            "import": {"path": str(hf_dir), "layout": "hf-llama"},
            "lora": {"rank": 4, "alpha": 8.0},
        })
        assert np.isfinite(summary["loss"])

    def test_builtin_import_without_lora_starts_from_checkpoint(
            self, tmp_path, monkeypatch, tiny_native):
        """Full-finetune import: step-0 loss equals the loss of the
        exported weights, proving the foreign tree actually landed."""
        cfg, params, _, _ = tiny_native
        convert.save_flat(params, str(tmp_path / "ckpt"))
        from polyaxon_tpu.runtime.builtin import run_builtin

        monkeypatch.chdir(tmp_path)
        monkeypatch.setenv("PLX_ARTIFACTS_PATH", str(tmp_path))
        common = dict(model="llama-tiny", steps=1, batch_size=8, seq_len=16,
                      checkpoint=False, watchdog=False,
                      parallelism={"fsdp": 2})
        with_import = run_builtin({
            **common, "import": {"path": str(tmp_path / "ckpt"),
                                 "layout": "flat"}})
        fresh = run_builtin(dict(common))
        # same data seed, different weights -> different first loss; the
        # imported run must NOT match the fresh-init loss
        assert with_import["loss"] != pytest.approx(fresh["loss"], abs=1e-6)

    def test_restarted_attempt_resumes_without_reimporting(
            self, tmp_path, monkeypatch, tiny_native):
        """Resume beats re-import: once a complete checkpoint exists in
        the artifacts dir, a restarted attempt must not pay the foreign
        tree read again (minutes of I/O at 7B)."""
        cfg, params, _, _ = tiny_native
        convert.save_flat(params, str(tmp_path / "ckpt"))
        from polyaxon_tpu.partition import convert as pconvert
        from polyaxon_tpu.runtime.builtin import run_builtin

        monkeypatch.chdir(tmp_path)
        monkeypatch.setenv("PLX_ARTIFACTS_PATH", str(tmp_path))
        calls = []
        orig = pconvert.import_params
        monkeypatch.setattr(
            pconvert, "import_params",
            lambda *a, **kw: calls.append(1) or orig(*a, **kw))
        spec = {"model": "llama-tiny", "steps": 2, "batch_size": 8,
                "seq_len": 16, "watchdog": False,
                "checkpoint": {"save_interval_steps": 1},
                "import": {"path": str(tmp_path / "ckpt"),
                           "layout": "flat"}}
        first = run_builtin(dict(spec))
        assert first["resumed_from_step"] == 0 and len(calls) == 1
        second = run_builtin({**spec, "steps": 3})
        assert second["resumed_from_step"] == 2
        assert len(calls) == 1, "restarted attempt re-imported the tree"


@pytest.mark.slow
class TestTwoProcessImportE2E:
    def test_multislice_import_pods(self, tmp_path):
        """2-process (2 virtual slices x 1 host) tpujob importing an
        HF-layout checkpoint through the rule engine: both pods join one
        jax.distributed program over a 2-slice mesh, each host reads only
        its shard slices, and the run succeeds with finite loss."""
        from polyaxon_tpu.api.store import Store
        from polyaxon_tpu.polyaxonfile import check_polyaxonfile
        from polyaxon_tpu.scheduler.agent import LocalAgent

        cfg = llama.LLAMA_TINY
        params = transformer.init(jax.random.PRNGKey(11), cfg)
        hf_dir = tmp_path / "hf_ckpt"
        convert.export_hf_llama(params, cfg, str(hf_dir))

        spec = check_polyaxonfile({
            "kind": "operation", "name": "ms-import",
            "component": {"kind": "component", "run": {
                "kind": "tpujob", "accelerator": "v5e", "topology": "2x2",
                "numSlices": 2,
                "parallelism": {"data": 2, "fsdp": 2},
                "runtime": {
                    "model": "llama-tiny", "steps": 2, "batch_size": 8,
                    "seq_len": 16, "checkpoint": False, "watchdog": False,
                    "platform": "cpu", "num_cpu_devices": 2,
                    "import": {"path": str(hf_dir), "layout": "hf-llama"},
                },
            }},
        }).to_dict()
        store = Store(":memory:")
        agent = LocalAgent(store, artifacts_root=str(tmp_path),
                           backend="cluster", poll_interval=0.05)
        uuid = store.create_run("p", spec=spec, name="msi")["uuid"]
        deadline = time.monotonic() + 300
        status = None
        try:
            while time.monotonic() < deadline:
                agent.tick()
                status = store.get_run(uuid)["status"]
                if status in ("succeeded", "failed", "stopped"):
                    break
                time.sleep(0.05)
            logs = "\n".join(
                agent.cluster.pod_logs(f"plx-{uuid[:12]}-{i}")
                for i in range(2))
            assert status == "succeeded", (store.get_statuses(uuid), logs)
            envs = agent.cluster.launched_env
            assert sorted(e["MEGASCALE_SLICE_ID"]
                          for e in envs.values()) == ["0", "1"]
        finally:
            agent.stop()
