"""V1Join materialization (upstream joins): an operation's joins query
finished runs and bind list params before compilation."""

import sys
import time

import pytest

from polyaxon_tpu.api.store import Store
from polyaxon_tpu.polyaxonfile import check_polyaxonfile
from polyaxon_tpu.scheduler.agent import LocalAgent
from polyaxon_tpu.scheduler.joins import materialize_joins, query_runs


class TestJoinQueries:
    def _store(self):
        store = Store(":memory:")
        for i, (st, loss) in enumerate([("succeeded", 3.0), ("succeeded", 1.0),
                                        ("failed", None), ("succeeded", 2.0)]):
            row = store.create_run("p", spec={}, name=f"r{i}",
                                   meta={}, inputs={"i": i})
            for s in ("compiled", "queued", "scheduled", "running"):
                store.transition(row["uuid"], s)
            store.transition(row["uuid"], st)
            if loss is not None:
                store.merge_outputs(row["uuid"], {"loss": loss})
        return store

    def test_query_filter_sort_limit(self):
        store = self._store()
        rows = query_runs(store, "p", {
            "query": "status:succeeded", "sort": "outputs.loss", "limit": 2,
        })
        assert [r["outputs"]["loss"] for r in rows] == [1.0, 2.0]

    def test_materialize_binds_lists(self):
        store = self._store()
        spec = {
            "kind": "operation",
            "joins": [{
                "query": "status:succeeded",
                "sort": "outputs.loss",
                "params": {"losses": {"value": "outputs.loss"},
                           "uuids": {"value": "uuid"}},
            }],
            "component": {"kind": "component"},
        }
        out = materialize_joins(store, "p", spec)
        assert out["params"]["losses"]["value"] == [1.0, 2.0, 3.0]
        assert len(out["params"]["uuids"]["value"]) == 3
        assert "joins" not in out

    def test_bad_query_term(self):
        with pytest.raises(ValueError, match="field:value"):
            query_runs(self._store(), "p", {"query": "nonsense"})


class TestJoinE2E:
    def test_join_feeds_aggregation_run(self, tmp_path):
        """Producer runs emit metrics; a join run receives all their losses
        as one list param (the upstream tuner-join pattern, SURVEY.md §3c)."""
        store = Store(":memory:")
        agent = LocalAgent(store, artifacts_root=str(tmp_path), poll_interval=0.05)

        def _producer(loss):
            return check_polyaxonfile({
                "kind": "operation", "name": f"prod-{loss}",
                "component": {"kind": "component", "run": {
                    "kind": "job", "container": {"command": [
                        sys.executable, "-c",
                        f"import json, os; json.dump({{'loss': {loss}}}, "
                        "open(os.path.join(os.environ['PLX_ARTIFACTS_PATH'],"
                        "'outputs.json'), 'w'))"]}},
                },
            }).to_dict()

        def _wait(uuid, timeout=60):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                agent.tick()
                cur = store.get_run(uuid)
                if cur["status"] in ("succeeded", "failed", "stopped", "skipped"):
                    return cur
                time.sleep(0.05)
            raise TimeoutError(store.get_statuses(uuid))

        try:
            for loss in (0.5, 0.25):
                assert _wait(store.create_run(
                    "p", spec=_producer(loss), name="x")["uuid"])["status"] == "succeeded"
            agg = check_polyaxonfile({
                "kind": "operation", "name": "agg",
                "joins": [{
                    "query": "status:succeeded",
                    "sort": "outputs.loss",
                    "params": {"losses": {"value": "outputs.loss"}},
                }],
                "component": {
                    "kind": "component",
                    "inputs": [{"name": "losses", "type": "list"}],
                    "run": {"kind": "job", "container": {"command": [
                        sys.executable, "-c",
                        "import json, os; losses = json.loads("
                        "os.environ['PLX_PARAMS'])['losses']; "
                        "json.dump({'best': min(losses)}, "
                        "open(os.path.join(os.environ['PLX_ARTIFACTS_PATH'],"
                        "'outputs.json'), 'w'))"]}},
                },
            }).to_dict()
            final = _wait(store.create_run("p", spec=agg, name="agg")["uuid"])
            assert final["status"] == "succeeded", store.get_statuses(final["uuid"])
            assert final["outputs"]["best"] == 0.25
        finally:
            agent.stop()
