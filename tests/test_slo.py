"""Tier-1 suite for the metrics-history recorder + SLO engine (ISSUE 20).

Four layers, matching the acceptance checklist:

- ring semantics: bucket keying, counter last-write vs gauge max
  downsampling, wraparound serving gaps (never a stale lap's data), and
  the counter-reset clamp in ``increase()``;
- burn-rate math against hand-computed windows for all four spec kinds
  (latency / ratio / events / gauge), on an injected monotonic clock;
- the alert state machine: transition dedup, the ``for_s`` dwell,
  re-notify intervals, and silent resolution of never-fired pendings;
- exactly-once transitions under a two-agent lease takeover: the
  deposed evaluator's fenced alert write dies with ``StaleLeaseError``
  and the transition counters record each edge exactly once.
"""

import threading
import time

import pytest

from polyaxon_tpu.api.store import FencedStore, StaleLeaseError, Store
from polyaxon_tpu.obs.history import (
    MetricsRecorder, SeriesBuffer, _Ring, increase, recorder_for,
)
from polyaxon_tpu.obs.metrics import MetricsRegistry
from polyaxon_tpu.obs.slo import (
    ALERT_PREFIX, AlertEngine, burn_rate, default_slo_pack, load_slo_pack,
    slo_status,
)
from polyaxon_tpu.schemas import V1SLO


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _recorder(clock, tiers=((10.0, 360), (120.0, 720))) -> MetricsRecorder:
    # allowlist=None: unit tests record arbitrary families directly
    return MetricsRecorder(MetricsRegistry(), interval_s=1.0, tiers=tiers,
                           allowlist=None, clock=clock)


# -- ring semantics ----------------------------------------------------------


class TestRing:
    def test_counter_keeps_last_write_in_bucket(self):
        r = _Ring(10.0, 8)
        r.record(2.0, 5.0, take_max=False)
        r.record(9.0, 3.0, take_max=False)  # same bucket, later sample
        pts = r.window(now=12.0, range_s=20.0)
        assert pts == [(2.0, 3.0), (0.0, None)]

    def test_gauge_keeps_bucket_max(self):
        r = _Ring(10.0, 8)
        r.record(2.0, 5.0, take_max=True)
        r.record(9.0, 3.0, take_max=True)  # lower later sample: max wins
        pts = r.window(now=12.0, range_s=20.0)
        assert pts == [(2.0, 5.0), (0.0, None)]

    def test_unwritten_buckets_read_as_gaps(self):
        r = _Ring(10.0, 8)
        r.record(15.0, 7.0, take_max=False)  # bucket 1 only
        pts = r.window(now=30.0, range_s=30.0)
        assert pts == [(10.0, 7.0), (0.0, None), (0.0, None)]

    def test_wraparound_never_serves_a_stale_lap(self):
        r = _Ring(10.0, 4)  # 40s of history
        r.record(5.0, 1.0, take_max=False)  # bucket 0 -> slot 0
        # a full lap later, bucket 4 maps onto slot 0: the stamp check
        # must report a gap, not the lap-old value 1.0
        pts = r.window(now=49.0, range_s=10.0, at=0.0)
        assert pts == [(0.0, None)]
        r.record(45.0, 9.0, take_max=False)  # resets the slot for bucket 4
        pts = r.window(now=49.0, range_s=10.0)
        assert pts == [(0.0, 9.0)]

    def test_increase_clamps_counter_resets(self):
        # 0 -> 100 -> restart (drops to 3) -> 10: increases are 100 + 7;
        # the reset contributes nothing instead of a negative cliff
        pts = [(40.0, 0.0), (30.0, 100.0), (20.0, None), (10.0, 3.0),
               (0.0, 10.0)]
        assert increase(pts) == pytest.approx(107.0)

    def test_recorder_downsamples_into_both_tiers(self):
        clock = FakeClock(0.0)
        rec = _recorder(clock, tiers=((10.0, 360), (120.0, 720)))
        # one sample per 10s bucket for 6 minutes, values ramping up
        for i in range(36):
            rec.observe("polyaxon_x_depth", float(i), now=i * 10.0 + 5.0)
        clock.t = 359.0
        fine = rec.query("polyaxon_x_depth", range_s=60.0)
        assert fine["interval_s"] == 10.0
        assert [v for _, v in fine["points"]] == [30.0, 31.0, 32.0, 33.0,
                                                  34.0, 35.0]
        # the coarse tier kept the MAX of each 120s bucket (gauge rule)
        coarse = rec.query("polyaxon_x_depth", range_s=7200.0)
        assert coarse["interval_s"] == 120.0
        vals = [v for _, v in coarse["points"] if v is not None]
        assert vals == [11.0, 23.0, 35.0]

    def test_series_cap_drops_instead_of_growing(self):
        clock = FakeClock(0.0)
        rec = _recorder(clock)
        import polyaxon_tpu.obs.history as hist_mod

        orig = hist_mod.MAX_SERIES
        hist_mod.MAX_SERIES = 3
        try:
            rec_max = 3
            for i in range(rec_max + 2):
                rec.observe("polyaxon_x_total", 1.0,
                            labels={"shard": str(i)}, kind="counter")
        finally:
            hist_mod.MAX_SERIES = orig
        assert len(rec._series) == 3
        assert rec.stats["dropped_series"] == 2


# -- fleet rollup ------------------------------------------------------------


class TestRollup:
    def test_series_buffer_roundtrip_lands_aged_points(self):
        pod_clock = FakeClock(1000.0)  # reporter clock, skewed arbitrarily
        buf = SeriesBuffer(clock=pod_clock)
        buf.add("polyaxon_x_queue", 4.0, labels={"replica": "0"})
        pod_clock.advance(20.0)
        buf.add("polyaxon_x_queue", 7.0, labels={"replica": "0"})
        payload = buf.drain()
        assert payload["series"][0]["points"][0][0] == pytest.approx(20.0)

        srv_clock = FakeClock(500.0)  # entirely different clock domain
        rec = _recorder(srv_clock)
        assert rec.ingest("run-abc", payload) == 2
        doc = rec.query("polyaxon_x_queue", range_s=60.0)
        assert doc["series"][0]["source"] == "run-abc"
        vals = [v for _, v in doc["points"] if v is not None]
        assert vals == [4.0, 7.0]
        assert buf.drain() is None  # drained buffers ship nothing

    def test_counters_sum_and_gauges_max_across_sources(self):
        clock = FakeClock(100.0)
        rec = _recorder(clock)
        for src, base in (("a", 10.0), ("b", 100.0)):
            rec.observe("polyaxon_x_total", base, kind="counter",
                        source=src, now=95.0)
            rec.observe("polyaxon_x_gauge", base, kind="gauge",
                        source=src, now=95.0)
        doc = rec.query("polyaxon_x_total", range_s=30.0)
        assert [v for _, v in doc["points"] if v is not None] == [110.0]
        doc = rec.query("polyaxon_x_gauge", range_s=30.0)
        assert [v for _, v in doc["points"] if v is not None] == [100.0]
        # counter increases also sum across the fleet
        rec.observe("polyaxon_x_total", 15.0, kind="counter", source="a",
                    now=105.0)
        rec.observe("polyaxon_x_total", 101.0, kind="counter", source="b",
                    now=105.0)
        assert rec.counter_increase("polyaxon_x_total", 30.0) == \
            pytest.approx(6.0)

    def test_ingest_rejects_junk_without_dying(self):
        rec = _recorder(FakeClock(10.0))
        assert rec.ingest("x", None) == 0
        assert rec.ingest("x", {"series": [
            {"family": "", "points": [[0, 1]]},
            {"family": "polyaxon_ok", "points": [[0, float("nan")],
                                                 [-5, 1.0], "junk",
                                                 [1.0, 2.0]]},
        ]}) == 1


# -- burn-rate math ----------------------------------------------------------


class TestBurnMath:
    def _clock_rec(self):
        clock = FakeClock(100.0)
        return clock, _recorder(clock)

    def test_ratio_burn_hand_computed(self):
        _, rec = self._clock_rec()
        # over the fast window: total 0 -> 1000, bad 0 -> 2.
        # err = 2/1000 = 0.002; objective 99.9% -> budget 0.001 -> burn 2
        for now, total, bad in ((55.0, 0.0, 0.0), (65.0, 1000.0, 2.0)):
            rec.observe("polyaxon_t_total", total, kind="counter", now=now)
            rec.observe("polyaxon_b_total", bad, kind="counter", now=now)
        spec = V1SLO.from_dict({
            "name": "avail", "kind": "ratio", "objective": 0.999,
            "bad_family": "polyaxon_b_total",
            "total_family": "polyaxon_t_total"})
        assert burn_rate(rec, spec, 60.0) == pytest.approx(2.0)

    def test_events_burn_hand_computed(self):
        _, rec = self._clock_rec()
        # 3 events in a 60s window = 180/hour; budget 5/hour -> burn 36
        rec.observe("polyaxon_e_total", 0.0, kind="counter", now=55.0)
        rec.observe("polyaxon_e_total", 3.0, kind="counter", now=65.0)
        spec = V1SLO.from_dict({
            "name": "ev", "kind": "events", "objective": 0.99,
            "family": "polyaxon_e_total", "budget_per_hour": 5.0})
        assert burn_rate(rec, spec, 60.0) == pytest.approx(36.0)

    def test_latency_burn_hand_computed(self):
        _, rec = self._clock_rec()
        # 100 observations in-window, 90 under the 0.1s bound.
        # err = 0.1; objective 95% -> budget 0.05 -> burn 2
        for now, le, count in ((55.0, 0.0, 0.0), (65.0, 90.0, 100.0)):
            rec.observe("polyaxon_l_seconds", le, kind="counter",
                        part="le", bound=0.1, now=now)
            rec.observe("polyaxon_l_seconds", count, kind="counter",
                        part="count", now=now)
        spec = V1SLO.from_dict({
            "name": "lat", "kind": "latency", "objective": 0.95,
            "family": "polyaxon_l_seconds", "threshold_s": 0.1})
        assert burn_rate(rec, spec, 60.0) == pytest.approx(2.0)

    def test_gauge_burn_is_breach_fraction_over_budget(self):
        _, rec = self._clock_rec()
        # 3 of 5 recorded buckets breaching (>= 1.0); objective 99% ->
        # burn = 0.6 / 0.01 = 60
        for now, v in ((55.0, 1.0), (65.0, 1.0), (75.0, 1.0),
                       (85.0, 0.0), (95.0, 0.0)):
            rec.observe("polyaxon_g_degraded", v, now=now)
        spec = V1SLO.from_dict({
            "name": "deg", "kind": "gauge", "objective": 0.99,
            "family": "polyaxon_g_degraded", "threshold": 1.0, "op": ">="})
        assert burn_rate(rec, spec, 60.0) == pytest.approx(60.0)

    def test_no_data_reads_as_burn_zero(self):
        _, rec = self._clock_rec()
        for spec in default_slo_pack():
            assert burn_rate(rec, spec, spec.fast_window_s) == 0.0

    def test_slo_status_flags_dual_window_breach_only(self):
        clock, rec = self._clock_rec()
        spec = V1SLO.from_dict({
            "name": "ev", "kind": "events", "objective": 0.99,
            "family": "polyaxon_e_total", "budget_per_hour": 5.0,
            "fast_window_s": 60.0, "slow_window_s": 600.0,
            "fast_burn": 2.0, "slow_burn": 2.0})
        # burst INSIDE the fast window but diluted across the slow
        # window's budget: fast breaches, slow doesn't -> no page
        rec.observe("polyaxon_e_total", 0.0, kind="counter", now=55.0)
        rec.observe("polyaxon_e_total", 1.0, kind="counter", now=65.0)
        (row,) = slo_status(rec, [spec])
        assert row["fast_burn"] >= 2.0
        assert row["slow_burn"] < 2.0
        assert row["breaching"] is False

    def test_yaml_pack_loads_through_the_schema_layer(self):
        specs = load_slo_pack(
            "slos:\n"
            "  - name: api-availability\n"
            "    kind: ratio\n"
            "    objective: 0.999\n"
            "    badFamily: polyaxon_b_total\n"
            "    totalFamily: polyaxon_t_total\n"
            "    forS: 30\n")
        assert specs[0].name == "api-availability"
        assert specs[0].for_s == 30.0

    def test_duplicate_slo_names_rejected(self):
        with pytest.raises(ValueError):
            load_slo_pack(
                "slos:\n"
                "  - {name: a, kind: events, family: polyaxon_e_total,\n"
                "     budget_per_hour: 1}\n"
                "  - {name: a, kind: events, family: polyaxon_e_total,\n"
                "     budget_per_hour: 2}\n")


# -- alert state machine -----------------------------------------------------


def _breaching_spec(**over) -> V1SLO:
    d = {"name": "ev", "kind": "events", "objective": 0.99,
         "family": "polyaxon_e_total", "budget_per_hour": 0.5,
         "fast_window_s": 60.0, "slow_window_s": 120.0,
         "fast_burn": 1.0, "slow_burn": 1.0, "for_s": 0.0}
    d.update(over)
    return V1SLO.from_dict(d)


def _inject_events(rec, clock, n=5.0):
    """Counter increase ``n`` inside both burn windows of the spec."""
    rec.observe("polyaxon_e_total", 0.0, kind="counter",
                now=clock.t - 15.0)
    rec.observe("polyaxon_e_total", n, kind="counter", now=clock.t - 5.0)


class TestAlertEngine:
    def setup_method(self):
        self.clock = FakeClock(1000.0)
        self.store = Store(":memory:")
        self.rec = _recorder(self.clock)
        self.events = []

    def _engine(self, spec, **kw):
        return AlertEngine(self.store, self.rec, specs=[spec],
                           notify=self.events.append, **kw)

    def test_fire_dedup_resolve_cycle_is_exactly_once(self):
        eng = self._engine(_breaching_spec())
        _inject_events(self.rec, self.clock)
        eng.evaluate_once()
        eng.evaluate_once()  # still breaching: same-state, no second fire
        assert [e["state"] for e in self.events] == ["firing"]
        assert self.store.stats["alert_transitions_firing"] == 1
        row = self.store.get_alert(ALERT_PREFIX + "ev")
        assert row["state"] == "firing" and row["transitions"] == 1

        # burn drains out of the windows -> resolved, notified once
        self.clock.advance(300.0)
        eng.evaluate_once()
        eng.evaluate_once()
        assert [e["state"] for e in self.events] == ["firing", "resolved"]
        assert self.store.stats["alert_transitions_resolved"] == 1
        assert self.store.get_alert(ALERT_PREFIX + "ev")["state"] == \
            "resolved"

    def test_firing_gauge_tracks_row_state(self):
        reg = self.store.metrics
        eng = self._engine(_breaching_spec())
        _inject_events(self.rec, self.clock)
        eng.evaluate_once()
        assert self.store._alerts_firing == 1
        g = reg.gauge("polyaxon_alerts_firing", "")
        assert g.value == 1.0
        self.clock.advance(300.0)
        eng.evaluate_once()
        assert g.value == 0.0

    def test_renotify_interval_gates_repeat_pages(self):
        # renotify 0: every evaluation while firing re-pages (marked
        # renotify=True), but records NO new transition
        eng = self._engine(_breaching_spec(renotify_interval_s=0.0))
        _inject_events(self.rec, self.clock)
        eng.evaluate_once()
        eng.evaluate_once()
        eng.evaluate_once()
        states = [(e["state"], e["renotify"]) for e in self.events]
        assert states == [("firing", False), ("firing", True),
                          ("firing", True)]
        assert self.store.stats["alert_transitions_firing"] == 1
        # a long interval suppresses the repeat page entirely
        self.events.clear()
        eng2 = self._engine(_breaching_spec(name="ev2",
                                            renotify_interval_s=3600.0))
        _inject_events(self.rec, self.clock)
        eng2.evaluate_once()
        eng2.evaluate_once()
        assert [e["renotify"] for e in self.events] == [False]

    def test_dwell_holds_pending_then_fires(self):
        eng = self._engine(_breaching_spec(for_s=0.15))
        _inject_events(self.rec, self.clock)
        eng.evaluate_once()
        assert self.events == []  # pending pages nobody
        assert self.store.get_alert(ALERT_PREFIX + "ev")["state"] == \
            "pending"
        eng.evaluate_once()  # dwell not yet served
        assert self.store.get_alert(ALERT_PREFIX + "ev")["state"] == \
            "pending"
        time.sleep(0.2)  # pending_at is a wall stamp (cross-process row)
        eng.evaluate_once()
        assert [e["state"] for e in self.events] == ["firing"]
        assert self.store.stats["alert_transitions_pending"] == 1
        assert self.store.stats["alert_transitions_firing"] == 1

    def test_pending_that_never_fired_resolves_silently(self):
        eng = self._engine(_breaching_spec(for_s=3600.0))
        _inject_events(self.rec, self.clock)
        eng.evaluate_once()
        assert self.store.get_alert(ALERT_PREFIX + "ev")["state"] == \
            "pending"
        self.clock.advance(300.0)  # breach gone before the dwell served
        eng.evaluate_once()
        assert self.store.get_alert(ALERT_PREFIX + "ev")["state"] == \
            "resolved"
        assert self.events == []  # nobody was paged, nobody gets all-clear

    def test_owns_filter_partitions_the_pack(self):
        specs = [_breaching_spec(name=f"ev{i}") for i in range(4)]
        seen = []
        eng = AlertEngine(self.store, self.rec, specs=specs,
                          owns=lambda name: (seen.append(name),
                                             name.endswith("2"))[1])
        _inject_events(self.rec, self.clock)
        out = eng.evaluate_once()
        assert [r["name"] for r in out] == [ALERT_PREFIX + "ev2"]
        assert len(seen) == 4

    def test_burn_gauge_registers_from_birth(self):
        reg = MetricsRegistry()
        self._engine(_breaching_spec(), registry=reg)
        text = reg.render()
        assert 'polyaxon_slo_burn_rate{slo="ev"}' in text


# -- exactly-once across a two-agent takeover --------------------------------


class TestTakeoverExactlyOnce:
    def test_deposed_evaluator_cannot_commit_or_notify(self):
        clock = FakeClock(1000.0)
        store = Store(":memory:")
        rec = _recorder(clock)
        spec = _breaching_spec()
        rec.observe("polyaxon_e_total", 0.0, kind="counter", now=985.0)
        rec.observe("polyaxon_e_total", 5.0, kind="counter", now=995.0)

        lease1 = store.acquire_lease("agent", "a1", ttl=0.05)
        f1 = FencedStore(store, lambda: ("agent", lease1["token"]))
        time.sleep(0.1)  # a1 hard-killed; its lease expires
        lease2 = store.acquire_lease("agent", "a2", ttl=30.0)
        assert lease2 is not None and lease2["token"] > lease1["token"]
        f2 = FencedStore(store, lambda: ("agent", lease2["token"]))

        paged1, paged2 = [], []
        eng1 = AlertEngine(f1, rec, specs=[spec], notify=paged1.append)
        eng2 = AlertEngine(f2, rec, specs=[spec], notify=paged2.append)

        # the corpse evaluates first: its fenced fire MUST die, recording
        # no transition and paging nobody
        with pytest.raises(StaleLeaseError):
            eng1.evaluate_once()
        assert paged1 == []
        assert store.stats["alert_transitions_firing"] == 0
        assert store.get_alert(ALERT_PREFIX + "ev") is None

        # the successor fires the same breach exactly once
        eng2.evaluate_once()
        eng2.evaluate_once()
        assert [e["state"] for e in paged2] == ["firing"]
        assert store.stats["alert_transitions_firing"] == 1
        assert store.stats["fence_rejections"] >= 1

        # the corpse coming back mid-episode reads the row, writes
        # nothing (same state, renotify interval unserved), pages nobody
        eng1.evaluate_once()
        assert paged1 == []
        assert store.stats["alert_transitions_firing"] == 1
        assert store.get_alert(ALERT_PREFIX + "ev")["transitions"] == 1

    def test_resolve_race_is_also_single_shot(self):
        clock = FakeClock(1000.0)
        store = Store(":memory:")
        rec = _recorder(clock)
        spec = _breaching_spec()
        rec.observe("polyaxon_e_total", 0.0, kind="counter", now=985.0)
        rec.observe("polyaxon_e_total", 5.0, kind="counter", now=995.0)

        lease1 = store.acquire_lease("agent", "a1", ttl=0.05)
        f1 = FencedStore(store, lambda: ("agent", lease1["token"]))
        eng1 = AlertEngine(f1, rec, specs=[spec],
                           notify=lambda e: None)
        eng1.evaluate_once()  # fires under a live lease
        assert store.stats["alert_transitions_firing"] == 1

        time.sleep(0.1)
        lease2 = store.acquire_lease("agent", "a2", ttl=30.0)
        f2 = FencedStore(store, lambda: ("agent", lease2["token"]))
        paged2 = []
        eng2 = AlertEngine(f2, rec, specs=[spec], notify=paged2.append)

        clock.advance(300.0)  # breach clears: both would resolve
        with pytest.raises(StaleLeaseError):
            eng1.evaluate_once()
        assert store.stats["alert_transitions_resolved"] == 0
        eng2.evaluate_once()
        eng2.evaluate_once()
        assert [e["state"] for e in paged2] == ["resolved"]
        assert store.stats["alert_transitions_resolved"] == 1


# -- recorder lifecycle ------------------------------------------------------


class TestRecorderLifecycle:
    def test_recorder_for_is_a_registry_singleton(self):
        reg = MetricsRegistry()
        a = recorder_for(reg, start=False)
        b = recorder_for(reg, start=False)
        assert a is b and a._thread is None

    def test_start_stop_sampler_thread(self):
        reg = MetricsRegistry()
        reg.gauge("polyaxon_agent_queue_depth", "x").set(3.0)
        rec = MetricsRecorder(reg, interval_s=0.01)
        rec.start()
        try:
            deadline = time.monotonic() + 2.0
            while rec.stats["samples"] == 0:
                assert time.monotonic() < deadline
                time.sleep(0.01)
        finally:
            rec.stop()
        assert not rec._thread.is_alive()
        doc = rec.query("polyaxon_agent_queue_depth", range_s=60.0)
        assert any(v == 3.0 for _, v in doc["points"])

    def test_sampler_skips_nan_and_offlist_families(self):
        reg = MetricsRegistry()
        reg.gauge("polyaxon_agent_queue_depth", "x").set(float("nan"))
        reg.gauge("polyaxon_not_allowlisted", "x").set(1.0)
        clock = FakeClock(50.0)
        rec = MetricsRecorder(reg, interval_s=1.0, clock=clock)
        rec.sample()
        assert rec.families() == []

    def test_concurrent_observe_and_query(self):
        rec = _recorder(time.monotonic)
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                rec.observe("polyaxon_x_total", float(i), kind="counter")
                i += 1

        threads = [threading.Thread(target=writer) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for _ in range(50):
                rec.query("polyaxon_x_total", range_s=60.0)
                rec.counter_increase("polyaxon_x_total", 60.0)
        finally:
            stop.set()
            for t in threads:
                t.join()
