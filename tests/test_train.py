"""Trainer tests: sharded end-to-end training step, loss goes down,
checkpoint save/resume round-trip (SURVEY.md §5 checkpoint/resume)."""

from dataclasses import replace

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from polyaxon_tpu.models import llama
from polyaxon_tpu.train import (
    CheckpointConfig,
    DataConfig,
    OptimizerConfig,
    Trainer,
    TrainerConfig,
    ThroughputMeter,
    make_batches,
    make_schedule,
)


def _trainer(tmp_path=None, parallelism=None, **opt):
    cfg = TrainerConfig(
        model=llama.LLAMA_TINY,
        optimizer=OptimizerConfig(
            learning_rate=1e-2, warmup_steps=2, total_steps=20, **opt
        ),
        batch_size=8,
        seq_len=32,
        parallelism=parallelism or {"data": 8},
        checkpoint=CheckpointConfig(
            directory=str(tmp_path), save_interval_steps=5, async_save=False
        ) if tmp_path else None,
        log_interval=2,
    )
    return cfg


class TestSchedules:
    def test_warmup_then_cosine(self):
        s = make_schedule(OptimizerConfig(learning_rate=1.0, warmup_steps=10, total_steps=110))
        assert float(s(0)) == 0.0
        assert abs(float(s(10)) - 1.0) < 1e-6
        assert float(s(110)) < float(s(50))

    def test_constant(self):
        s = make_schedule(OptimizerConfig(learning_rate=0.5, warmup_steps=0,
                                          total_steps=10, schedule="constant"))
        assert float(s(7)) == 0.5


class TestTrainer:
    def test_loss_decreases(self):
        import itertools

        cfg = _trainer()
        tr = Trainer(cfg)
        batch = next(make_batches(
            DataConfig(kind="synthetic-lm", batch_size=8, seq_len=32,
                       vocab_size=cfg.model.vocab_size), tr.mesh,
        ))
        data = itertools.repeat(batch)  # memorize one batch: loss must fall
        logs = []
        tr.track = lambda step, m: logs.append(m)
        state, final = tr.fit(data, num_steps=12)
        assert int(state.step) == 12
        assert final["loss"] < logs[0]["loss"] - 0.3
        assert final["tokens_per_sec"] > 0

    def test_sharded_params_materialize_sharded(self):
        cfg = _trainer(parallelism={"fsdp": 4, "model": 2})
        tr = Trainer(cfg)
        state = tr.init_state()
        # mlp wi: (L, hidden, mlp) sharded fsdp on hidden, model on mlp
        wi = state.params["layers"]["mlp"]["wi"]
        shard = wi.addressable_shards[0].data
        assert shard.shape[1] == wi.shape[1] // 4
        assert shard.shape[2] == wi.shape[2] // 2

    def test_tensor_parallel_training(self):
        cfg = _trainer(parallelism={"data": 2, "model": 2, "context": 2})
        tr = Trainer(cfg)
        data = make_batches(
            DataConfig(kind="synthetic-lm", batch_size=8, seq_len=32,
                       vocab_size=cfg.model.vocab_size), tr.mesh,
        )
        state, final = tr.fit(data, num_steps=3)
        assert np.isfinite(final["loss"])

    def test_checkpoint_resume(self, tmp_path):
        cfg = _trainer(tmp_path=tmp_path / "ckpt")
        tr = Trainer(cfg)
        data = make_batches(
            DataConfig(kind="synthetic-lm", batch_size=8, seq_len=32,
                       vocab_size=cfg.model.vocab_size), tr.mesh,
        )
        state, _ = tr.fit(data, num_steps=10)
        w_trained = np.asarray(state.params["embed"]["tokens"])

        tr2 = Trainer(_trainer(tmp_path=tmp_path / "ckpt"))
        state2, step = tr2.restore_or_init()
        assert step == 10
        np.testing.assert_allclose(
            np.asarray(state2.params["embed"]["tokens"]), w_trained, atol=1e-7
        )

    def test_resume_continues_from_step(self, tmp_path):
        cfg = _trainer(tmp_path=tmp_path / "ckpt")
        tr = Trainer(cfg)
        data = make_batches(
            DataConfig(kind="synthetic-lm", batch_size=8, seq_len=32,
                       vocab_size=cfg.model.vocab_size), tr.mesh,
        )
        tr.fit(data, num_steps=5)
        tr2 = Trainer(_trainer(tmp_path=tmp_path / "ckpt"))
        state2, final = tr2.fit(data, num_steps=8)  # resumes at 5, runs 3 more
        assert int(state2.step) == 8


class TestMeter:
    def test_mfu_math(self):
        m = ThroughputMeter(tokens_per_step=1000, flops_per_token=1e9,
                            num_chips=2, accelerator="v5e")
        m.elapsed, m.steps = 1.0, 10
        assert m.tokens_per_sec == 10000
        assert m.tokens_per_sec_per_chip == 5000
        # 5000 * 1e9 / 1e12 = 5 TFLOP/s vs 197 peak
        assert abs(m.mfu - 5.0 / 197.0) < 1e-6


class TestLowmemAdam:
    """scale_by_adam_lowmem in f32 must match optax.adamw step-for-step;
    bf16 moments must stay close (storage rounding only)."""

    def _updates(self, tx, params, grads, steps=3):
        state = tx.init(params)
        for _ in range(steps):
            upd, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, upd)
        return params

    def test_f32_matches_optax_adamw(self):
        from polyaxon_tpu.train.optimizers import OptimizerConfig, make_optimizer

        params = {"w": jnp.linspace(-1, 1, 32).reshape(4, 8)}
        grads = {"w": jnp.linspace(0.5, -0.5, 32).reshape(4, 8)}
        base = OptimizerConfig(learning_rate=1e-2, warmup_steps=0,
                               schedule="constant", total_steps=10, grad_clip=0)
        ref = self._updates(make_optimizer(base), params, grads)
        low = self._updates(
            make_optimizer(replace(base, nu_dtype="float32")), params, grads)
        assert jnp.allclose(ref["w"], low["w"], atol=1e-6), (ref["w"] - low["w"])

    def test_bf16_moments_close(self):
        from polyaxon_tpu.train.optimizers import OptimizerConfig, make_optimizer

        params = {"w": jnp.linspace(-1, 1, 32).reshape(4, 8)}
        grads = {"w": jnp.linspace(0.5, -0.5, 32).reshape(4, 8)}
        base = OptimizerConfig(learning_rate=1e-2, warmup_steps=0,
                               schedule="constant", total_steps=10, grad_clip=0)
        ref = self._updates(make_optimizer(base), params, grads)
        low = self._updates(
            make_optimizer(replace(base, mu_dtype="bfloat16", nu_dtype="bfloat16")),
            params, grads)
        # moments rounded to bf16: updates agree to ~1e-2 relative
        assert jnp.allclose(ref["w"], low["w"], atol=5e-4), (ref["w"] - low["w"]).max()


class TestGradAccumulation:
    """microbatches=k must match the single-shot step on the same global
    batch (grads averaged over microbatches == grads over full batch)."""

    def test_microbatch_parity(self):
        from polyaxon_tpu.train import (
            DataConfig, OptimizerConfig, Trainer, TrainerConfig, make_batches,
        )

        mcfg = llama.LLAMA_TINY
        base = dict(
            model=mcfg,
            optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                      schedule="constant", total_steps=4),
            batch_size=32, seq_len=32, parallelism={"data": 8},
        )
        losses = {}
        for k in (1, 4):
            tr = Trainer(TrainerConfig(**base, microbatches=k))
            data = make_batches(DataConfig(kind="synthetic-lm", batch_size=32,
                                           seq_len=32, vocab_size=mcfg.vocab_size,
                                           seed=7), tr.mesh)
            state, metrics = tr.fit(data, num_steps=4)
            losses[k] = metrics["loss"]
        assert abs(losses[1] - losses[4]) < 1e-3, losses

    def test_indivisible_microbatches_rejected(self):
        from polyaxon_tpu.train import OptimizerConfig, Trainer, TrainerConfig

        tr = Trainer(TrainerConfig(
            model=llama.LLAMA_TINY, optimizer=OptimizerConfig(total_steps=1),
            batch_size=8, seq_len=32, parallelism={"data": 1}, microbatches=3,
        ))
        with pytest.raises(ValueError, match="divisible"):
            tr.make_step()

    def test_bf16_accumulator_close_to_f32(self):
        from polyaxon_tpu.train import (
            DataConfig, OptimizerConfig, Trainer, TrainerConfig, make_batches,
        )

        mcfg = llama.LLAMA_TINY
        base = dict(
            model=mcfg,
            optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                      schedule="constant", total_steps=4),
            batch_size=32, seq_len=32, parallelism={"data": 8}, microbatches=4,
        )
        losses = {}
        for ad in (None, "bfloat16"):
            tr = Trainer(TrainerConfig(**base, accum_dtype=ad))
            data = make_batches(DataConfig(kind="synthetic-lm", batch_size=32,
                                           seq_len=32, vocab_size=mcfg.vocab_size,
                                           seed=7), tr.mesh)
            _, metrics = tr.fit(data, num_steps=4)
            losses[ad] = metrics["loss"]
        assert abs(losses[None] - losses["bfloat16"]) < 5e-3, losses
